"""Static invariant analyzer (tpu_perf.analysis, `tpu-perf lint`).

Every rule gets paired good/bad fixtures (each bad snippet must produce
exactly its expected finding; each good snippet and each
pragma-suppressed site must be clean), seeded bad-fixture MUTATIONS of
the real call sites the rules exist to protect (a rank-conditional stop
vote, a wall clock in the fault injector, a 20th ResultRow field with no
parser branch, a half-wired seventh log family, an unguarded
_canon-style access), and a self-check that the live tree lints clean
against the checked-in (empty) baseline.
"""

import json
import os
import textwrap

import pytest

import tpu_perf
from tpu_perf.analysis import (
    default_manifest_path, default_root, lint_tree, load_manifest,
    render_baseline,
)
from tpu_perf.analysis.engine import all_rules, resolve_rules
from tpu_perf.cli import main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(
    tpu_perf.__file__)))


def make_tree(tmp_path, files, manifest_extra=None):
    """Write a fixture tree + manifest; returns (root, manifest_path)."""
    for rel, content in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(content))
    data = {"version": 1, "include": ["pkg/**/*.py"]}
    if manifest_extra:
        data.update(manifest_extra)
    mpath = tmp_path / "manifest.json"
    mpath.write_text(json.dumps(data))
    return str(tmp_path), str(mpath)


def run_lint(tmp_path, files, manifest_extra=None, rules=None,
             baseline=None):
    root, mpath = make_tree(tmp_path, files, manifest_extra)
    manifest = load_manifest(mpath, root)
    return lint_tree(root, manifest,
                     rules=resolve_rules(rules) if rules else None,
                     baseline_path=baseline)


ZONES = {"deterministic_zones": ["pkg/det/"]}


# ------------------------------------------------------------------ R1

def test_r1_bad_wallclock_in_zone(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                return time.time()
            """,
    }, ZONES)
    assert [(f.rule, f.line) for f in res.findings] == [("R1", 4)]
    assert "time.time" in res.findings[0].message
    assert res.findings[0].scope == "stamp"


def test_r1_good_zone_seeded_and_injected(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import random

            import numpy as np

            _RNG = random.Random(7)
            _GEN = np.random.default_rng(7)

            def draw(perf_clock):
                return _RNG.random(), perf_clock()
            """,
    }, ZONES)
    assert res.findings == []


def test_r1_unseeded_rng_constructors_flagged(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import random

            import numpy as np

            def bad():
                a = random.Random()
                b = np.random.default_rng()
                c = np.random.rand(3)
                return a, b, c
            """,
    }, ZONES)
    assert sorted(f.line for f in res.findings) == [6, 7, 8]
    assert all(f.rule == "R1" for f in res.findings)


def test_r1_import_alias_resolved(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import time as _t

            def stamp():
                return _t.monotonic()
            """,
    }, ZONES)
    assert [f.rule for f in res.findings] == ["R1"]
    assert "time.monotonic" in res.findings[0].message


def test_r1_pragma_suppresses_inline_and_above(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                return time.time()  # tpuperf: allow-clock(ledger filename only)

            def stamp2():
                # tpuperf: allow-clock(operator display timestamp)
                return time.monotonic()
            """,
    }, ZONES)
    assert res.findings == []
    assert len(res.suppressed) == 2
    assert {s["pragma"]["arg"] for s in res.suppressed} == {
        "ledger filename only", "operator display timestamp"}
    assert len([p for p in res.pragmas if p.kind == "allow-clock"]) == 2


def test_r1_clock_param_bypass_outside_zone(tmp_path):
    # NOT a zone file: the injectable-clock routing check applies
    # everywhere
    res = run_lint(tmp_path, {
        "pkg/timingish.py": """\
            import time

            def measure(step, perf_clock=time.perf_counter):
                t0 = time.perf_counter()
                step()
                return perf_clock() - t0

            def fine(step, perf_clock=time.perf_counter):
                t0 = perf_clock()
                step()
                return perf_clock() - t0

            def also_fine():
                return time.perf_counter()
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R1", 4)]
    assert "perf_clock" in res.findings[0].message


def test_r1_inline_pragma_does_not_bleed_to_next_line(tmp_path):
    # an inline waiver covers exactly the audited site; the unaudited
    # clock read on the NEXT line must still be a finding
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                a = time.time()  # tpuperf: allow-clock(audited site)
                b = time.time()
                return a, b
            """,
    }, ZONES)
    assert [(f.rule, f.line) for f in res.findings] == [("R1", 5)]
    assert len(res.suppressed) == 1


# ------------------------------------------------------------------ R2

def test_r2_rank_conditional_collective(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def vote(self, local):
                    if self.rank == 0:
                        return allreduce_times(1.0 if local else 0.0)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 6)]
    assert "allreduce_times" in res.findings[0].message


def test_r2_timing_taint_propagates_through_assignment(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import psum

            def drain(perf_clock, t0):
                t = perf_clock()
                budget = t - t0
                while budget > 0:
                    psum(1)
                    budget -= 1
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 7)]


def test_r2_early_exit_before_collective(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def hb(self, samples):
                    if self.rank != 0:
                        return
                    allreduce_times(samples)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 5)]
    assert "early exit" in res.findings[0].message


def test_r2_rank_conditioned_stream_plan(tmp_path):
    # the streams contract (tpu_perf.streams.plans): a wave plan must
    # be a pure function of static config.  A plan that gates a lane's
    # dispatch on rank desynchronizes the wave's collective order
    # across ranks — the engine fences in dispatch order, so the other
    # ranks hang in a collective this rank never entered
    res = run_lint(tmp_path, {
        "pkg/waves.py": """\
            from somewhere import ppermute

            class Engine:
                def drain_wave(self, lanes):
                    for lane in lanes:
                        if self.rank == lane:
                            ppermute(lane)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 7)]
    assert "lockstep" in res.findings[0].message


def test_r2_static_round_robin_stream_plan_clean(tmp_path):
    # the good twin: static round-robin waves (tpu_perf.streams.plans
    # .wave_plan's shape) — lane membership and order derive from the
    # plan and K alone, so every rank walks the identical dispatch
    # sequence and R2 stays silent
    res = run_lint(tmp_path, {
        "pkg/waves.py": """\
            from somewhere import ppermute

            def drain_waves(points, k):
                for start in range(0, len(points), k):
                    for lane, point in enumerate(points[start:start + k]):
                        ppermute((lane, point))
            """,
    })
    assert res.findings == []


def test_r2_rank_conditioned_artifact_lookup_caught(tmp_path):
    # the tuner anti-pattern: consulting the selection table under a
    # rank (or clock) condition picks DIFFERENT algorithms on different
    # ranks — each rank then dispatches a different collective program
    # and the mesh deadlocks.  The lookup itself is fine; the branch is
    # the bug
    res = run_lint(tmp_path, {
        "pkg/auto.py": """\
            from somewhere import psum

            class Plan:
                def dispatch(self, table, op, nbytes):
                    if self.rank == 0:
                        algo = table.get((op, nbytes), "native")
                        psum((op, algo))
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 7)]


def test_r2_static_plan_time_artifact_lookup_clean(tmp_path):
    # the good twin (tuner.LoadedSelection.resolve's shape): the winner
    # is a pure function of (table, point) — rank-independent data flow
    # into the collective is legal, only CONTROL dependence desyncs the
    # dispatch order
    res = run_lint(tmp_path, {
        "pkg/auto.py": """\
            from somewhere import psum

            def dispatch(table, points):
                for op, nbytes in points:
                    algo = table.get((op, nbytes), "native")
                    psum((op, algo))
            """,
    })
    assert res.findings == []


def test_r2_uniform_conditions_and_trailing_rank_exit_clean(tmp_path):
    # the real _heartbeat shape: uniform n_hosts guard, collective,
    # THEN the rank-0-only reporting exit
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def hb(self, samples):
                    x = None
                    if self.n_hosts > 1:
                        x = allreduce_times(samples)
                    if self.rank != 0:
                        return
                    print(x)
            """,
    })
    assert res.findings == []


def test_r2_rank_local_argument_is_legal(tmp_path):
    # data dependence is the POINT of a vote; only control dependence
    # desyncs the mesh
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            def vote(rank, local):
                return allreduce_times(1.0 if local else float(rank))
            """,
    })
    assert res.findings == []


def test_r2_rank_exit_inside_nested_function_is_clean(tmp_path):
    # a return inside a closure exits only the closure — it cannot skip
    # the outer function's collective
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def hb(self, samples):
                    def log_if_leader(msg):
                        if self.rank != 0:
                            return
                        print(msg)
                    x = allreduce_times(samples)
                    log_if_leader(x)
            """,
    })
    assert res.findings == []


def test_r2_rank_tainted_assert_before_collective_caught(tmp_path):
    # `assert rank == 0` is a conditional raise: non-matching ranks
    # skip the collective; a uniform assert stays legal
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            def bad(rank, payload):
                assert rank == 0
                return allreduce_times(payload)

            def good(n_hosts, payload):
                assert n_hosts > 1
                return allreduce_times(payload)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 4)]


def test_r2_rank_exit_in_else_branch_caught(tmp_path):
    # the exit hiding in the ELSE arm splits the mesh exactly like one
    # in the body
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            def f(rank):
                if rank == 0:
                    pass
                else:
                    return
                allreduce_times(1.0)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 4)]


def test_r2_tainted_loop_iteration_count_caught(tmp_path):
    # a rank-dependent TRIP COUNT varies the per-rank entry count
    # exactly like a rank-tainted test; a plan-driven loop stays legal
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times, psum

            class C:
                def bad(self):
                    for _ in range(self.rank):
                        allreduce_times(1.0)

                def bad_comp(self):
                    return [psum(1) for _ in range(self.rank)]

                def good(self, plan):
                    for _ in plan:
                        allreduce_times(1.0)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 6),
                                                        ("R2", 9)]


def test_suppressed_findings_carry_fingerprints(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                return time.time()  # tpuperf: allow-clock(audited)
            """,
    }, ZONES)
    assert res.findings == []
    (entry,) = res.suppressed
    assert entry["finding"]["fingerprint"]
    assert len(entry["finding"]["fingerprint"]) == 12


def test_r2_rank_break_in_loop_before_collective_is_clean(tmp_path):
    # break/continue exit only the loop; a rank-local poll loop BEFORE a
    # collective is lockstep-legal (every rank still reaches the call) —
    # but a rank-conditional break INSIDE the collective's own loop
    # changes the per-rank collective count and must be flagged
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            def poll_then_sync(rank, items):
                for it in items:
                    if rank == 0:
                        break
                allreduce_times(1.0)

            def desync(rank, items):
                for it in items:
                    if rank == 0:
                        break
                    allreduce_times(it)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 11)]


def test_r2_helper_returning_rank_no_longer_launders_taint(tmp_path):
    # the one-level interprocedural summary (PR-8 follow-on): a helper
    # returning self.rank is itself a taint source, whether its result
    # guards the collective directly or through an assignment
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def _lucky(self):
                    return self.rank

                def vote(self):
                    if self._lucky():
                        allreduce_times(1.0)

                def vote2(self):
                    lead = self._lucky()
                    if lead == 0:
                        allreduce_times(2.0)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == \
        [("R2", 9), ("R2", 14)]


def test_r2_helper_returning_uniform_state_is_clean(tmp_path):
    # the paired good fixture: a helper whose return derives from
    # uniform state must NOT register as a source — and the summary is
    # one level deep by design, so a helper returning ANOTHER helper's
    # result does not propagate (documented limit, not an accident)
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def _hosts(self):
                    return self.n_hosts

                def _indirect(self):
                    return self._lucky()

                def _lucky(self):
                    return self.rank

                def vote(self):
                    if self._hosts() > 1:
                        allreduce_times(1.0)

                def vote2(self):
                    if self._indirect():
                        allreduce_times(2.0)
            """,
    })
    assert res.findings == []


def test_r2_helper_tainted_early_exit_caught(tmp_path):
    # the early-exit scan sees through the helper too
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            def _owner(rank):
                return rank == 0

            class C:
                def hb(self, samples):
                    if not _owner(self.kind):
                        return
                    allreduce_times(samples)
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R2", 8)]
    assert "early exit" in res.findings[0].message


def test_r2_pragma_audits_site(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def replay(self):
                    if self.rank == 0:
                        allreduce_times(4.0)  # tpuperf: allow-lockstep(single-rank replay tool)
            """,
    })
    assert res.findings == []
    assert len(res.suppressed) == 1


# ------------------------------------------------------------------ R3

GOOD_SCHEMA = textwrap.dedent("""\
    A_PREFIX = "a"
    B_PREFIX = "b"
    ALL_PREFIXES = (A_PREFIX, B_PREFIX)
    HDR = "x,y,z"

    class Row:
        x: int
        y: int
        z: int

        @classmethod
        def from_csv(cls, line):
            parts = line.split(",")
            if len(parts) not in (2, 3):
                raise ValueError(line)
            return cls()
    """)

GOOD_PIPELINE = textwrap.dedent("""\
    from pkg.schema import A_PREFIX, B_PREFIX, ALL_PREFIXES

    def IngestionProperties(**kw):
        return kw

    class K:
        def __init__(self):
            self._a = IngestionProperties(table="A")
            self._b = IngestionProperties(table="B")

        def ingest(self, name):
            if name.startswith(B_PREFIX):
                return self._b
            return self._a

    def sweep():
        lazy_families = (B_PREFIX,)
        return lazy_families
    """)

R34_MANIFEST = {
    "family_contract": {
        "schema": "pkg/schema.py", "ingest": "pkg/pipeline.py",
        "csv_families": ["A_PREFIX"], "default_family": "A_PREFIX",
    },
    "schema_drift": {
        "schema": "pkg/schema.py", "row_class": "Row",
        "header_const": "HDR",
    },
}


def test_r3_r4_good_pair_clean(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": GOOD_PIPELINE,
    }, R34_MANIFEST)
    assert res.findings == []


def test_r3_seventh_family_half_wired(tmp_path):
    schema = GOOD_SCHEMA.replace(
        'B_PREFIX = "b"', 'B_PREFIX = "b"\nC_PREFIX = "c"'
    ).replace(
        "ALL_PREFIXES = (A_PREFIX, B_PREFIX)",
        "ALL_PREFIXES = (A_PREFIX, B_PREFIX, C_PREFIX)",
    )
    res = run_lint(tmp_path, {
        "pkg/schema.py": schema,
        "pkg/pipeline.py": GOOD_PIPELINE,
    }, R34_MANIFEST)
    msgs = [f.message for f in res.findings]
    assert all(f.rule == "R3" for f in res.findings)
    assert any("no startswith() routing branch" in m for m in msgs)
    assert any("missing from lazy_families" in m for m in msgs)
    assert any("IngestionProperties" in m for m in msgs)


def test_r3_declared_but_unswept_family(tmp_path):
    schema = GOOD_SCHEMA.replace('B_PREFIX = "b"',
                                 'B_PREFIX = "b"\nC_PREFIX = "c"')
    res = run_lint(tmp_path, {
        "pkg/schema.py": schema,
        "pkg/pipeline.py": GOOD_PIPELINE,
    }, R34_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "missing from ALL_PREFIXES" in res.findings[0].message


def test_r3_zero_table_routes_is_loud_not_disabled(tmp_path):
    # a refactor that removes every IngestionProperties call must fail
    # the table surface, not silently retire the check
    pipeline = GOOD_PIPELINE.replace("IngestionProperties(table=\"A\")",
                                     "object()").replace(
                                     "IngestionProperties(table=\"B\")",
                                     "object()")
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": pipeline,
    }, R34_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "no IngestionProperties table routes" in res.findings[0].message


def test_r3_csv_family_in_lazy_set(tmp_path):
    pipeline = GOOD_PIPELINE.replace("lazy_families = (B_PREFIX,)",
                                     "lazy_families = (A_PREFIX, B_PREFIX)")
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": pipeline,
    }, R34_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "swept mid-row" in res.findings[0].message


GOOD_SINKS = textwrap.dedent("""\
    from pkg.schema import A_PREFIX, B_PREFIX

    PUSH_ROUTES = {
        A_PREFIX: "A",
    }

    TEE_FREE_FAMILIES = (B_PREFIX,)
    """)

R3_PUSH_MANIFEST = {
    "family_contract": {
        "schema": "pkg/schema.py", "ingest": "pkg/pipeline.py",
        "push": "pkg/sinks.py",
        "csv_families": ["A_PREFIX"], "default_family": "A_PREFIX",
    },
}


def test_r3_push_partition_clean(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": GOOD_PIPELINE,
        "pkg/sinks.py": GOOD_SINKS,
    }, R3_PUSH_MANIFEST)
    assert res.findings == []


def test_r3_family_missing_from_push_partition(tmp_path):
    # a family in neither PUSH_ROUTES nor TEE_FREE_FAMILIES is the
    # half-wired eighth family: it rotates, but never reaches a live
    # sink, and nothing says that was a choice
    sinks = GOOD_SINKS.replace("TEE_FREE_FAMILIES = (B_PREFIX,)",
                               "TEE_FREE_FAMILIES = ()")
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": GOOD_PIPELINE,
        "pkg/sinks.py": sinks,
    }, R3_PUSH_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "neither routed in PUSH_ROUTES" in res.findings[0].message


def test_r3_tee_free_family_gaining_a_route_is_caught(tmp_path):
    # the chaos-ledger contract: a byte-identity family can never be
    # both excluded and routed
    sinks = GOOD_SINKS.replace('A_PREFIX: "A",',
                               'A_PREFIX: "A",\n    B_PREFIX: "B",')
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": GOOD_PIPELINE,
        "pkg/sinks.py": sinks,
    }, R3_PUSH_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "tee-free AND routed" in res.findings[0].message


def test_r3_push_surface_missing_routes_is_loud(tmp_path):
    # a refactor that renames PUSH_ROUTES must fail the surface, not
    # silently retire the check
    sinks = GOOD_SINKS.replace("PUSH_ROUTES", "ROUTES")
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": GOOD_PIPELINE,
        "pkg/sinks.py": sinks,
    }, R3_PUSH_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "PUSH_ROUTES dict" in res.findings[0].message


def test_r3_push_surface_not_linted_is_a_finding(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/schema.py": GOOD_SCHEMA,
        "pkg/pipeline.py": GOOD_PIPELINE,
    }, R3_PUSH_MANIFEST)
    assert [f.rule for f in res.findings] == ["R3"]
    assert "push surface" in res.findings[0].message


# ------------------------------------------------------------------ R4

def test_r4_new_field_without_parser_width(tmp_path):
    schema = GOOD_SCHEMA.replace("    z: int\n", "    z: int\n    w: int\n")
    res = run_lint(tmp_path, {
        "pkg/schema.py": schema,
        "pkg/pipeline.py": GOOD_PIPELINE,
    }, R34_MANIFEST)
    assert [f.rule for f in res.findings] == ["R4"]
    assert "4 fields" in res.findings[0].message
    assert "top out at 3" in res.findings[0].message


def test_r4_header_width_must_be_accepted(tmp_path):
    schema = GOOD_SCHEMA.replace('HDR = "x,y,z"', 'HDR = "x,y,z,w"')
    res = run_lint(tmp_path, {
        "pkg/schema.py": schema,
        "pkg/pipeline.py": GOOD_PIPELINE,
    }, R34_MANIFEST)
    assert [f.rule for f in res.findings] == ["R4"]
    assert "4 columns" in res.findings[0].message


# ------------------------------------------------------------------ R5

LOCKED = textwrap.dedent("""\
    import threading

    class D:
        def __init__(self):
            self._lock = threading.Lock()
            self._refs = {}  # tpuperf: guarded-by(_lock)

        def adopt(self, key):
            with self._lock:
                self._refs[key] = self._refs.get(key, 0) + 1
    """)


def test_r5_guarded_access_clean(tmp_path):
    res = run_lint(tmp_path, {"pkg/locks.py": LOCKED})
    assert res.findings == []


def test_r5_unguarded_access_flagged(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/locks.py": LOCKED + textwrap.indent(textwrap.dedent("""\

        def peek(self, key):
            return self._refs.get(key)
        """), "    "),
    })
    assert [f.rule for f in res.findings] == ["R5"]
    assert "_refs" in res.findings[0].message
    assert "_lock" in res.findings[0].message


def test_r5_allow_unguarded_pragma(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/locks.py": LOCKED + textwrap.indent(textwrap.dedent("""\

        def size(self):
            return len(self._refs)  # tpuperf: allow-unguarded(monitoring read of a dict len)
        """), "    "),
    })
    assert res.findings == []
    assert len(res.suppressed) == 1


def test_r5_multi_target_assignment_guards_every_attribute(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/locks.py": """\
            import threading

            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._a = self._b = 0  # tpuperf: guarded-by(_lock)

                def bump(self):
                    self._b += 1
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R5", 9)]
    assert "_b" in res.findings[0].message


def test_r5_same_named_lock_on_other_receiver_does_not_guard(tmp_path):
    # holding another object's same-named lock is a real race, not a
    # guarded access; a local alias NAMED AFTER the lock stays accepted
    # (an arbitrarily-named alias needs an allow-unguarded pragma)
    res = run_lint(tmp_path, {
        "pkg/locks.py": LOCKED + textwrap.indent(textwrap.dedent("""\

        def cross(self, other):
            with other._lock:
                return self._refs

        def aliased(self):
            _lock = self._lock
            with _lock:
                return self._refs
        """), "    "),
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R5", 14)]


def test_r5_tuple_unpacking_assignment_guards_every_attribute(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/locks.py": """\
            import threading

            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._a, self._b = 0, 1  # tpuperf: guarded-by(_lock)

                def bump(self):
                    self._b += 1
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R5", 9)]
    assert "_b" in res.findings[0].message


def test_r5_standalone_above_guarded_by_pragma(tmp_path):
    # the documented standalone-above placement works for guarded-by
    # too, and the assignment below it is the exempt declaration
    res = run_lint(tmp_path, {
        "pkg/locks.py": """\
            import threading

            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                    # tpuperf: guarded-by(_lock)
                    self._refs = {}

                def adopt(self, key):
                    with self._lock:
                        self._refs[key] = 1

                def peek(self):
                    return self._refs
            """,
    })
    assert [(f.rule, f.line) for f in res.findings] == [("R5", 14)]


def test_r5_pragma_on_multiline_declaration_continuation(tmp_path):
    # a pragma on the continuation line exempts the WHOLE declaring
    # statement, including the target's earlier line
    res = run_lint(tmp_path, {
        "pkg/locks.py": """\
            import threading

            class D:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._refs: dict = (
                        {})  # tpuperf: guarded-by(_lock)

                def adopt(self, key):
                    with self._lock:
                        self._refs[key] = 1
            """,
    })
    assert res.findings == []


def test_r5_scope_is_the_declaring_class(tmp_path):
    # an unrelated class reusing the attribute name is a different
    # attribute, not a violation of the declarer's lock contract
    res = run_lint(tmp_path, {
        "pkg/locks.py": LOCKED + textwrap.dedent("""\

        class Unrelated:
            def __init__(self):
                self._refs = []

            def touch(self):
                return len(self._refs)
        """),
    })
    assert res.findings == []


def test_r2_attribute_assignment_does_not_taint_receiver(tmp_path):
    # `self.t = perf_clock()` binds no local name; the receiver `self`
    # must not become tainted, or every uniform `if self.<flag>:` guard
    # in the method would falsely flag its collective
    res = run_lint(tmp_path, {
        "pkg/vote.py": """\
            from somewhere import allreduce_times

            class C:
                def hb(self, perf_clock, vals):
                    self.t_last = perf_clock()
                    if self.enabled:
                        allreduce_times(vals)
            """,
    })
    assert res.findings == []


def test_r5_misplaced_guarded_by_pragma(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/locks.py": """\
            # tpuperf: guarded-by(_lock)
            X = 1
            """,
    })
    assert [f.rule for f in res.findings] == ["R5"]
    assert "not attached" in res.findings[0].message


# ------------------------------------------------------------------ R6

def test_r6_zone_matching_no_files_is_flagged(tmp_path):
    # a renamed faults/ module must not silently shrink the R1 zone
    res = run_lint(tmp_path, {
        "pkg/other.py": "x = 1\n",
    }, {"deterministic_zones": ["pkg/det/", "pkg/missing.py"]},
        rules=["R6"])
    assert sorted(f.message.split("'")[1] for f in res.findings) == [
        "pkg/det/", "pkg/missing.py"]
    assert all(f.rule == "R6" for f in res.findings)
    # anchored at the manifest, where the fix happens
    assert all(f.path == "manifest.json" for f in res.findings)


def test_r6_covered_zones_are_clean(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": "x = 1\n",
        "pkg/spans.py": "y = 2\n",
    }, {"deterministic_zones": ["pkg/det/", "pkg/spans.py"]},
        rules=["R6"])
    assert res.findings == []


def test_r6_fires_alongside_the_other_rules(tmp_path):
    # default rule set: the stale zone is a finding next to R1's
    res = run_lint(tmp_path, {
        "pkg/other.py": "x = 1\n",
    }, {"deterministic_zones": ["pkg/gone/"]})
    assert [f.rule for f in res.findings] == ["R6"]


# -------------------------------------------------------------- pragmas

def test_unknown_and_malformed_pragmas_are_findings(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/x.py": """\
            A = 1  # tpuperf: allow-clocks(typo)
            B = 2  # tpuperf: allow-clock
            C = 3  # tpuperf: allow-clock()
            """,
    })
    assert [f.rule for f in res.findings] == ["P0", "P0", "P0"]
    msgs = " ".join(f.message for f in res.findings)
    assert "unknown pragma directive" in msgs
    assert "malformed pragma" in msgs
    assert "requires a" in msgs


def test_prose_mention_of_marker_is_not_a_pragma(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/x.py": """\
            # engine docs: write '# tpuperf: allow-clock(reason)' to waive
            A = 1
            """,
    })
    assert res.findings == []
    assert res.pragmas == []


def test_syntax_error_is_a_parse_finding(tmp_path):
    res = run_lint(tmp_path, {"pkg/x.py": "def broken(:\n"})
    assert [f.rule for f in res.findings] == ["P1"]


def test_indentation_error_is_a_parse_finding_not_a_crash(tmp_path):
    # tokenize raises IndentationError (not TokenError) on bad dedents;
    # the lint must degrade to a P1 finding, never a traceback
    res = run_lint(tmp_path, {
        "pkg/x.py": "def f():\n        x = 1\n    y = 2\n",
    })
    assert [f.rule for f in res.findings] == ["P1"]


# ------------------------------------- mutations of the real call sites

def _real(relpath):
    with open(os.path.join(REPO_ROOT, relpath), encoding="utf-8") as fh:
        return fh.read()


def test_mutation_rank_conditional_stop_vote_caught(tmp_path):
    """The acceptance scenario: the real adaptive.py's unanimous-vote
    allreduce made rank-conditional must be caught by R2 — this is the
    bug class that deadlocks (or silently skews) a 256-chip mesh and
    never fires on a healthy CI runner."""
    src = _real("tpu_perf/adaptive.py")
    needle = "elif self.n_hosts > 1:"
    assert needle in src
    mutated = src.replace(needle, "elif self.rank == 0:", 1)
    res = run_lint(tmp_path, {"pkg/adaptive.py": mutated},
                   {"deterministic_zones": ["pkg/adaptive.py"]})
    r2 = [f for f in res.findings if f.rule == "R2"]
    assert len(r2) == 1
    assert "allreduce_times" in r2[0].message
    # and the unmutated file is clean
    clean = run_lint(tmp_path, {"pkg/adaptive.py": src},
                     {"deterministic_zones": ["pkg/adaptive.py"]})
    assert clean.findings == []


def test_mutation_rank_helper_laundered_vote_caught(tmp_path):
    """The interprocedural acceptance scenario: the real adaptive.py's
    vote guard routed through a helper returning rank state — the
    laundering shape the one-level summary exists to close (a bare
    intra-function walk sees only an innocent method call)."""
    src = _real("tpu_perf/adaptive.py")
    needle = "    def should_stop(self, runs_done: int, *, tracer=None) -> bool:"
    assert needle in src
    mutated = src.replace(
        needle,
        "    def _leader(self):\n"
        "        return self.rank == 0\n\n" + needle,
        1,
    ).replace("elif self.n_hosts > 1:", "elif self._leader():", 1)
    res = run_lint(tmp_path, {"pkg/adaptive.py": mutated},
                   {"deterministic_zones": ["pkg/adaptive.py"]})
    r2 = [f for f in res.findings if f.rule == "R2"]
    assert len(r2) == 1
    assert "allreduce_times" in r2[0].message
    clean = run_lint(tmp_path, {"pkg/adaptive.py": src},
                     {"deterministic_zones": ["pkg/adaptive.py"]})
    assert clean.findings == []


def test_mutation_wallclock_in_fault_injector_caught(tmp_path):
    """A time.time() slipped into the fault injector would silently break
    the byte-identical-ledger-per-seed contract; R1 rejects it at parse
    time."""
    src = _real("tpu_perf/faults/injector.py")
    needle = "import random"
    assert needle in src
    mutated = src.replace(
        needle, "import random\nimport time\n_SEEDED_AT = time.time()", 1)
    res = run_lint(tmp_path, {"pkg/faults/injector.py": mutated},
                   {"deterministic_zones": ["pkg/faults/"]})
    assert [f.rule for f in res.findings] == ["R1"]
    assert "time.time" in res.findings[0].message
    clean = run_lint(tmp_path, {"pkg/faults/injector.py": src},
                     {"deterministic_zones": ["pkg/faults/"]})
    assert clean.findings == []


REAL_CONTRACT_MANIFEST = {
    "family_contract": {
        "schema": "pkg/schema.py", "ingest": "pkg/pipeline.py",
        "push": "pkg/sinks.py",
        "csv_families": ["LEGACY_PREFIX", "EXT_PREFIX"],
        "default_family": "LEGACY_PREFIX",
    },
    "schema_drift": {
        "schema": "pkg/schema.py", "row_class": "ResultRow",
        "header_const": "RESULT_HEADER",
    },
}


def test_mutation_25th_resultrow_field_caught(tmp_path):
    """The acceptance scenario: a 25th ResultRow column with no parser
    branch fails lint (R4), not production replay (the 24th, load,
    shipped with its parser width — this proves the NEXT one cannot
    ship without it)."""
    schema = _real("tpu_perf/schema.py")
    # the FIELD line (decorate_op's parameter shares the spelling, so
    # the needle pins the dataclass declaration's trailing comment)
    needle = "    imbalance: int = 1       # per-rank payload ratio"
    assert needle in schema
    mutated = schema.replace(
        needle, "    imbalance: int = 1\n    queue_depth: int = 0  #", 1)
    res = run_lint(tmp_path, {
        "pkg/schema.py": mutated,
        "pkg/pipeline.py": _real("tpu_perf/ingest/pipeline.py"),
        "pkg/sinks.py": _real("tpu_perf/push/sinks.py"),
    }, REAL_CONTRACT_MANIFEST)
    assert [f.rule for f in res.findings] == ["R4"]
    assert "25 fields" in res.findings[0].message


def test_mutation_eighth_family_caught(tmp_path):
    """A ninth *_PREFIX family added to schema.py without ingest
    routing / lazy wiring / a Kusto table is caught by R3 on every
    missing surface (the eighth, tune, shipped fully wired)."""
    schema = _real("tpu_perf/schema.py")
    mutated = schema.replace(
        "ALL_PREFIXES = (LEGACY_PREFIX, EXT_PREFIX, HEALTH_PREFIX, "
        "CHAOS_PREFIX,\n                LINKMAP_PREFIX, SPANS_PREFIX, "
        "FLEET_PREFIX, TUNE_PREFIX)",
        'POWER_PREFIX = "power"\n'
        "ALL_PREFIXES = (LEGACY_PREFIX, EXT_PREFIX, HEALTH_PREFIX, "
        "CHAOS_PREFIX,\n                LINKMAP_PREFIX, SPANS_PREFIX, "
        "FLEET_PREFIX, TUNE_PREFIX, POWER_PREFIX)",
        1,
    )
    assert mutated != schema
    res = run_lint(tmp_path, {
        "pkg/schema.py": mutated,
        "pkg/pipeline.py": _real("tpu_perf/ingest/pipeline.py"),
        "pkg/sinks.py": _real("tpu_perf/push/sinks.py"),
    }, REAL_CONTRACT_MANIFEST)
    msgs = [f.message for f in res.findings]
    assert all(f.rule == "R3" for f in res.findings)
    assert any("POWER_PREFIX has no startswith() routing" in m
               for m in msgs)
    assert any("POWER_PREFIX is missing from lazy_families" in m
               for m in msgs)
    assert any("IngestionProperties" in m for m in msgs)
    assert any("neither routed in PUSH_ROUTES nor" in m and
               "POWER_PREFIX" in m for m in msgs)
    # the real, unmutated pair is clean
    clean = run_lint(tmp_path, {
        "pkg/schema.py": schema,
        "pkg/pipeline.py": _real("tpu_perf/ingest/pipeline.py"),
        "pkg/sinks.py": _real("tpu_perf/push/sinks.py"),
    }, REAL_CONTRACT_MANIFEST)
    assert clean.findings == []


def test_mutation_unguarded_canon_access_caught(tmp_path):
    """An unguarded read of the compile pipeline's worker/consumer state
    (the _canon_lock analogue) is caught by R5."""
    src = _real("tpu_perf/compilepipe.py")
    needle = "    def close(self, timeout: float = 60.0) -> None:"
    assert needle in src
    mutated = src.replace(
        needle,
        "    def peek(self, key):\n"
        "        return self._results.get(key)\n\n" + needle,
        1,
    )
    res = run_lint(tmp_path, {"pkg/compilepipe.py": mutated})
    assert [f.rule for f in res.findings] == ["R5"]
    assert "_results" in res.findings[0].message
    clean = run_lint(tmp_path, {"pkg/compilepipe.py": src})
    assert clean.findings == []


# --------------------------------------------- fingerprints & baseline

def test_fingerprints_survive_line_drift(tmp_path):
    files = {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                return time.time()
            """,
    }
    res1 = run_lint(tmp_path / "a", files, ZONES)
    shifted = {
        "pkg/det/inj.py": "# a comment\n# another\n\n"
        + textwrap.dedent(files["pkg/det/inj.py"]),
    }
    res2 = run_lint(tmp_path / "b", shifted, ZONES)
    assert len(res1.findings) == len(res2.findings) == 1
    assert res1.findings[0].line != res2.findings[0].line
    assert res1.findings[0].fingerprint == res2.findings[0].fingerprint


def test_duplicate_sites_get_distinct_fingerprints(tmp_path):
    res = run_lint(tmp_path, {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                a = time.time()
                b = time.time()
                return a, b
            """,
    }, ZONES)
    assert len(res.findings) == 2
    assert len({f.fingerprint for f in res.findings}) == 2


def test_baseline_roundtrip_and_stale_detection(tmp_path):
    files = {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                return time.time()
            """,
    }
    res = run_lint(tmp_path / "a", files, ZONES)
    base = tmp_path / "baseline.json"
    base.write_text(render_baseline(res.findings))
    res2 = run_lint(tmp_path / "b", files, ZONES, baseline=str(base))
    assert res2.unbaselined == []
    assert all(f.baselined for f in res2.findings)
    # a retired fingerprint is reported stale, never silently kept
    base.write_text(json.dumps(
        {"version": 1, "findings": [{"fingerprint": "deadbeefcafe"}]}))
    res3 = run_lint(tmp_path / "c", files, ZONES, baseline=str(base))
    assert len(res3.unbaselined) == 1
    assert res3.baseline_stale == ["deadbeefcafe"]


# ------------------------------------------------- live-tree self-check

def test_live_tree_lints_clean_against_checked_in_baseline():
    """The dogfood contract: the shipped baseline is EMPTY and the live
    tree produces zero findings against the checked-in manifest."""
    baseline_path = os.path.join(
        REPO_ROOT, "tpu_perf", "analysis", "baseline.json")
    with open(baseline_path) as fh:
        assert json.load(fh)["findings"] == [], \
            "the shipped baseline must stay empty — fix findings instead"
    manifest = load_manifest(default_manifest_path(), default_root())
    res = lint_tree(default_root(), manifest, baseline_path=baseline_path)
    assert res.unbaselined == [], "\n".join(
        f.render() for f in res.unbaselined)
    # the sanctioned escape hatches are visible, not silent
    assert any(p.kind == "allow-clock" for p in res.pragmas)
    assert any(p.kind == "guarded-by" for p in res.pragmas)


def test_rule_catalog_covers_r1_to_r6():
    ids = [r.id for r in all_rules()]
    assert ids == ["R1", "R2", "R3", "R4", "R5", "R6"]
    for rule in all_rules():
        assert rule.doc(), f"{rule.id} ships without docs"


# ----------------------------------------------------------------- CLI

def _cli_tree(tmp_path):
    root, mpath = make_tree(tmp_path, {
        "pkg/det/inj.py": """\
            import time

            def stamp():
                return time.time()
            """,
    }, ZONES)
    return root, mpath


def test_cli_lint_text_and_exit_code(tmp_path, capsys):
    root, mpath = _cli_tree(tmp_path)
    rc = main(["lint", root, "--manifest", mpath])
    assert rc == 8
    out = capsys.readouterr().out
    assert "R1(no-wallclock)" in out
    assert "1 finding(s)" in out


def test_cli_lint_json_schema(tmp_path, capsys):
    root, mpath = _cli_tree(tmp_path)
    rc = main(["lint", root, "--manifest", mpath, "--format", "json"])
    assert rc == 8
    data = json.loads(capsys.readouterr().out)
    assert data["version"] == 1
    assert data["summary"]["unbaselined"] == 1
    assert data["summary"]["findings"] == 1
    assert data["summary"]["suppressed"] == 0
    (finding,) = data["findings"]
    for key in ("rule", "name", "path", "line", "col", "scope",
                "message", "snippet", "fingerprint", "baselined"):
        assert key in finding
    assert {r["id"] for r in data["rules"]} == {"R1", "R2", "R3",
                                                "R4", "R5", "R6"}
    assert data["baseline"] == {"path": None, "matched": 0, "stale": []}


def test_cli_lint_rule_selection(tmp_path, capsys):
    root, mpath = _cli_tree(tmp_path)
    rc = main(["lint", root, "--manifest", mpath, "--rule", "R2,R5"])
    assert rc == 0  # the R1 finding is filtered out
    rc = main(["lint", root, "--manifest", mpath, "--rule",
               "no-wallclock"])
    assert rc == 8
    capsys.readouterr()
    assert main(["lint", root, "--manifest", mpath,
                 "--rule", "nonsense"]) == 2
    assert "unknown rule" in capsys.readouterr().err
    # a selector that dissolves to nothing must not run zero checks and
    # report the tree clean
    assert main(["lint", root, "--manifest", mpath, "--rule", ","]) == 2
    assert "selected no rules" in capsys.readouterr().err


def test_cli_lint_write_baseline_then_clean(tmp_path, capsys):
    root, mpath = _cli_tree(tmp_path)
    base = os.path.join(root, "lint-baseline.json")
    rc = main(["lint", root, "--manifest", mpath, "--baseline", base,
               "--write-baseline"])
    assert rc == 0
    rc = main(["lint", root, "--manifest", mpath, "--baseline", base])
    assert rc == 0
    capsys.readouterr()
    # a missing baseline is a config error, not a silent no-baseline run
    assert main(["lint", root, "--manifest", mpath, "--baseline",
                 os.path.join(root, "nope.json")]) == 2


def test_cli_lint_list_rules(capsys):
    rc = main(["lint", "--list-rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for token in ("R1 (no-wallclock)", "R2 (lockstep)",
                  "R3 (family-contract)", "R4 (schema-drift)",
                  "R5 (guarded-by)"):
        assert token in out


def test_cli_lint_defaults_to_live_tree(capsys):
    """`tpu-perf lint` with no arguments lints the installed package's
    repo with the checked-in manifest — and that tree is clean."""
    rc = main(["lint"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_exclude_patterns_respect_path_boundaries(tmp_path):
    files = {
        "pkg/det/gen/tool.py": "import time\nT = time.time()\n",
        "pkg/det/genuine.py": "import time\nT = time.time()\n",
    }
    # excluding the gen/ DIRECTORY must not swallow genuine.py
    res = run_lint(tmp_path, files, {
        "deterministic_zones": ["pkg/det/"],
        "exclude": ["pkg/det/gen/**"],
    })
    assert [f.path for f in res.findings] == ["pkg/det/genuine.py"]
    # and a bare prefix with no boundary excludes nothing extra
    res2 = run_lint(tmp_path / "b", files, {
        "deterministic_zones": ["pkg/det/"],
        "exclude": ["pkg/det/gen"],
    })
    assert sorted(f.path for f in res2.findings) == [
        "pkg/det/gen/tool.py", "pkg/det/genuine.py"]
    # a single '*' stays inside one path segment: "pkg/det/gen*" matches
    # genuine.py (same segment) but must NOT descend into gen/
    res3 = run_lint(tmp_path / "c", files, {
        "deterministic_zones": ["pkg/det/"],
        "exclude": ["pkg/det/gen*"],
    })
    assert [f.path for f in res3.findings] == ["pkg/det/gen/tool.py"]


def test_cli_write_baseline_requires_baseline_path(tmp_path, capsys):
    root, mpath = _cli_tree(tmp_path)
    assert main(["lint", root, "--manifest", mpath,
                 "--write-baseline"]) == 2
    assert "--baseline" in capsys.readouterr().err
    # an unwritable baseline path is a config error (exit 2), never a
    # traceback
    assert main(["lint", root, "--manifest", mpath, "--baseline",
                 os.path.join(root, "no-such-dir", "b.json"),
                 "--write-baseline"]) == 2
    assert "cannot write baseline" in capsys.readouterr().err


def test_manifest_validation(tmp_path):
    bad = tmp_path / "m.json"
    bad.write_text(json.dumps({"version": 1, "zone": ["x"]}))
    with pytest.raises(ValueError, match="unknown key"):
        load_manifest(str(bad), str(tmp_path))
    bad.write_text(json.dumps({"version": 2}))
    with pytest.raises(ValueError, match="unsupported version"):
        load_manifest(str(bad), str(tmp_path))
    bad.write_text(json.dumps({"version": 1,
                               "deterministic_zones": "notalist"}))
    with pytest.raises(ValueError, match="string list"):
        load_manifest(str(bad), str(tmp_path))
