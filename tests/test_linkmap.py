"""Link-map subsystem (ISSUE 3): planner link-disjointness, MAD grading
on synthetic matrices with planted faults, record round-trips, ingest
routing, and the end-to-end localization contract through the CLI."""

import json
import math

import pytest

from tpu_perf.cli import main
from tpu_perf.linkmap import (
    GradeConfig,
    LinkmapRecord,
    LinkProbe,
    LinkProber,
    ProbeResult,
    all_links,
    grade,
    plan_all_pairs,
    plan_mesh_links,
    probe_op_name,
    read_linkmap,
)
from tpu_perf.linkmap.probe import LinkMapResult

# --- planner ------------------------------------------------------------


def _assert_schedule_disjoint(sched):
    links = [(p.src, p.dst) for p in sched.probes]
    assert len(set(links)) == len(links), sched.name
    assert len({s for s, _ in links}) == len(links), sched.name
    assert len({d for _, d in links}) == len(links), sched.name


@pytest.mark.parametrize("shape", [(8,), (2, 4), (2, 2, 2)])
def test_plan_covers_every_directed_neighbor_link_once(shape):
    schedules = plan_mesh_links(shape)
    for s in schedules:
        _assert_schedule_disjoint(s)
    seen = [(p.src, p.dst) for p in all_links(schedules)]
    assert len(seen) == len(set(seen))  # no link probed twice
    # expected directed torus links: per axis, 2 per device (±1), except
    # size-2 axes where +1 and -1 name the same two directed links
    n = math.prod(shape)
    expected = sum(n * (1 if s == 2 else 2) for s in shape if s >= 2)
    assert len(seen) == expected
    # spot-check coordinates round-trip through the probe op name
    p = all_links(schedules)[0]
    assert p.op == probe_op_name(p.src_coords, p.dst_coords)
    assert p.op.startswith("link:(")


def test_plan_1d_links_are_ring_neighbors():
    (fwd, back) = plan_mesh_links((4,), ("x",))
    assert {(p.src, p.dst) for p in fwd.probes} == \
        {(0, 1), (1, 2), (2, 3), (3, 0)}
    assert {(p.src, p.dst) for p in back.probes} == \
        {(1, 0), (2, 1), (3, 2), (0, 3)}
    assert fwd.name == "x[+1]" and back.name == "x[-1]"
    assert all(p.axis == "x" for p in fwd.probes)


def test_plan_no_wrap_drops_torus_edges():
    schedules = plan_mesh_links((4,), ("x",), wrap=False)
    seen = {(p.src, p.dst) for p in all_links(schedules)}
    assert seen == {(0, 1), (1, 2), (2, 3), (1, 0), (2, 1), (3, 2)}


def test_plan_size_one_axis_has_no_links():
    schedules = plan_mesh_links((1, 4), ("dcn", "ici"))
    assert {s.name for s in schedules} == {"ici[+1]", "ici[-1]"}


def test_plan_rejects_bad_shapes():
    with pytest.raises(ValueError, match="bad mesh shape"):
        plan_mesh_links(())
    with pytest.raises(ValueError, match="length mismatch"):
        plan_mesh_links((2, 4), ("x",))


@pytest.mark.parametrize("n", [2, 5, 6, 8])
def test_all_pairs_tournament_covers_all_ordered_pairs(n):
    schedules = plan_all_pairs(n)
    for s in schedules:
        _assert_schedule_disjoint(s)
    seen = [(p.src, p.dst) for p in all_links(schedules)]
    assert len(seen) == len(set(seen))
    assert set(seen) == {(i, j) for i in range(n) for j in range(n) if i != j}


def test_all_pairs_needs_two_endpoints():
    with pytest.raises(ValueError, match=">= 2"):
        plan_all_pairs(1)


# --- grading on synthetic matrices --------------------------------------


def _probe_result(src, dst, samples, *, shape=(2, 4), iters=1, nbytes=1024,
                  dropped=0, rank=0):
    from tpu_perf.linkmap.plan import coords_of

    probe = LinkProbe(src=src, dst=dst, src_coords=coords_of(src, shape),
                      dst_coords=coords_of(dst, shape), axis="ax1", shift=1)
    return ProbeResult(probe=probe, rank=rank, host="h", samples=samples,
                       dropped=dropped, first_run=1, last_run=1,
                       iters=iters, nbytes=nbytes)


def _result(probes, n=8):
    return LinkMapResult(n=n, shape=(2, 4), axes=("ax0", "ax1"),
                         nbytes=1024, iters=1, runs=len(probes[0].samples),
                         fence="block", concurrent=False, synthetic=True,
                         probes=probes)


def _synthetic_sweep(slow=(), dead=(), base=1e-4, factor=4.0):
    """A full 2x4 neighbor sweep with near-flat times, some links
    planted slow (x factor) or dead (all samples lost)."""
    probes = []
    for i, p in enumerate(all_links(plan_mesh_links((2, 4)))):
        t = base * (1.0 + 1e-3 * ((i * 7919) % 13 - 6))  # deterministic noise
        if (p.src, p.dst) in slow:
            t *= factor
        if (p.src, p.dst) in dead:
            probes.append(ProbeResult(probe=p, rank=0, host="h", samples=[],
                                      dropped=3, first_run=1, last_run=1,
                                      iters=1, nbytes=1024))
            continue
        probes.append(ProbeResult(probe=p, rank=0, host="h",
                                  samples=[t, t * 1.0005, t * 0.9995],
                                  dropped=0, first_run=1, last_run=1,
                                  iters=1, nbytes=1024))
    return _result(probes)


def test_grade_clean_sweep_is_all_ok():
    verdicts = grade(_synthetic_sweep())
    assert [v.verdict for v in verdicts] == ["ok"] * len(verdicts)
    assert all(v.mad_z is not None for v in verdicts)


def test_grade_localizes_planted_slow_link():
    verdicts = grade(_synthetic_sweep(slow={(1, 2)}))
    sick = [v for v in verdicts if v.verdict != "ok"]
    assert [(v.src, v.dst, v.verdict) for v in sick] == [(1, 2, "slow")]
    (v,) = sick
    assert v.op == "link:(0,1)>(0,2)"  # flat 1->2 on a 2x4 mesh
    assert "row/col median" in v.detail and v.rel == pytest.approx(3.0,
                                                                   rel=0.05)


def test_grade_dead_links():
    # all-samples-lost is dead; an extreme slowdown past dead_ratio too
    verdicts = grade(_synthetic_sweep(dead={(2, 3)}, slow={(5, 6)},
                                      factor=50.0))
    by_link = {(v.src, v.dst): v for v in verdicts}
    assert by_link[(2, 3)].verdict == "dead"
    assert "no surviving samples" in by_link[(2, 3)].detail
    # even with no samples the verdict carries the peer-median baseline,
    # so the critical event still names what healthy looks like
    assert by_link[(2, 3)].baseline_us == pytest.approx(100.0, rel=0.01)
    assert by_link[(5, 6)].verdict == "dead"
    assert "dead ratio" in by_link[(5, 6)].detail
    assert sum(1 for v in verdicts if v.verdict != "ok") == 2


def test_grade_mean_keeps_single_spike_visible():
    """The per-probe statistic is the MEAN: one 30x stall among 5
    samples must still flag the link (a median would hide it)."""
    probes = []
    for p in all_links(plan_mesh_links((2, 4))):
        samples = [1e-4] * 5
        if (p.src, p.dst) == (6, 7):
            samples[2] = 30e-4
        probes.append(ProbeResult(probe=p, rank=0, host="h",
                                  samples=samples, dropped=0, first_run=1,
                                  last_run=1, iters=1, nbytes=1024))
    verdicts = grade(_result(probes))
    sick = [(v.src, v.dst) for v in verdicts if v.verdict != "ok"]
    assert sick == [(6, 7)]


def test_grade_roofline_floor():
    # two links, no MAD signal (tiny population falls back to peers),
    # but bandwidth far under the roofline floor -> slow
    probes = [
        _probe_result(0, 1, [1e-4]),   # 1024 B / 1e-4 s = 0.01024 GB/s
        _probe_result(1, 0, [1e-4]),
    ]
    cfg = GradeConfig(roofline_gbps=45.0, roofline_floor=0.5)
    verdicts = grade(_result(probes, n=2), cfg)
    assert all(v.verdict == "slow" for v in verdicts)
    assert all("roofline" in v.detail for v in verdicts)
    assert verdicts[0].roofline_frac == pytest.approx(0.01024 / 45.0)
    # a roofline verdict's baseline is the roofline-implied latency, not
    # the (equally-slow) peer median — the event must show the real gap
    assert verdicts[0].baseline_us == pytest.approx(
        1024 / (45.0 * 1e9) * 1e6)
    assert verdicts[0].baseline_us < verdicts[0].lat_us
    # same sweep without a roofline: nothing to judge against -> ok
    assert all(v.verdict == "ok" for v in grade(_result(probes, n=2)))


def test_grade_peers_are_axis_scoped():
    """Heterogeneous meshes: a (dcn, ici) sweep's DCN links are
    legitimately ~10x the ICI links — peers must come from the SAME
    axis, or every healthy DCN link grades dead."""
    def sweep(dcn_factor):
        probes = []
        for i, p in enumerate(all_links(plan_mesh_links((2, 4),
                                                        ("dcn", "ici")))):
            t = 1e-4 * (1.0 + 1e-3 * ((i * 7919) % 13 - 6))
            if p.axis == "dcn":
                t *= 10.0          # a different fabric, healthily slower
            if (p.src, p.dst) == (1, 5):  # a dcn link: flat 1 -> 5
                t *= dcn_factor
            probes.append(ProbeResult(probe=p, rank=0, host="h",
                                      samples=[t], dropped=0, first_run=1,
                                      last_run=1, iters=1, nbytes=1024))
        return _result(probes)

    assert all(v.verdict == "ok" for v in grade(sweep(1.0)))
    sick = [v for v in grade(sweep(4.0)) if v.verdict != "ok"]
    assert [(v.src, v.dst, v.axis, v.verdict) for v in sick] == \
        [(1, 5, "dcn", "slow")]


def test_grade_roofline_axes_scope():
    """The chip's ici_gbps models ICI links only: with roofline_axes
    set, a dcn/pair probe is neither annotated nor judged against it."""
    probes = [
        _probe_result(0, 1, [1e-4]),  # axis ax1 (the helper's default)
        _probe_result(1, 0, [1e-4]),
    ]
    cfg = GradeConfig(roofline_gbps=45.0, roofline_axes=("ici",))
    verdicts = grade(_result(probes, n=2), cfg)
    assert all(v.verdict == "ok" for v in verdicts)
    assert all(v.roofline_frac is None for v in verdicts)
    cfg = GradeConfig(roofline_gbps=45.0, roofline_axes=("ax1",))
    verdicts = grade(_result(probes, n=2), cfg)
    assert all(v.verdict == "slow" for v in verdicts)


def test_grade_config_validation():
    with pytest.raises(ValueError, match="roofline_floor"):
        GradeConfig(roofline_floor=1.5)
    with pytest.raises(ValueError, match="dead_ratio"):
        GradeConfig(dead_ratio=0.5)
    with pytest.raises(ValueError, match="roofline_gbps"):
        GradeConfig(roofline_gbps=-1.0)


# --- records ------------------------------------------------------------


def test_linkmap_record_round_trip():
    rec = LinkmapRecord(record="probe", op="link:(0)>(1)", src=0, dst=1)
    back = LinkmapRecord.from_json(rec.to_csv())
    assert back.data == rec.data
    with pytest.raises(ValueError, match="discriminator"):
        LinkmapRecord(op="x")
    with pytest.raises(ValueError, match="not a linkmap record"):
        LinkmapRecord.from_json('{"op": "x"}')
    with pytest.raises(ValueError, match="bad linkmap record"):
        LinkmapRecord.from_json("{nope")


def test_read_linkmap_replays_newest_sweep(tmp_path, capsys):
    """A fleet log folder accumulates one linkmap file per sweep —
    multiple sweeps are the NORMAL state: replay groups records per
    sweep by the meta's job_id and renders the newest (by mtime), with
    a note naming the skipped older sweeps."""
    import os
    import time as _time

    a = tmp_path / "linkmap-u-0-a.log"
    a.write_text(json.dumps({"record": "meta", "job_id": "x", "n": 2}) + "\n"
                 + json.dumps({"record": "verdict", "src": 0, "dst": 1,
                               "verdict": "ok"}) + "\n")
    meta, probes, verdicts = read_linkmap([str(a)])
    assert meta["n"] == 2 and len(verdicts) == 1 and probes == []
    b = tmp_path / "linkmap-u-0-b.log"
    b.write_text(json.dumps({"record": "meta", "job_id": "y", "n": 4}) + "\n"
                 + json.dumps({"record": "verdict", "src": 2, "dst": 3,
                               "verdict": "slow"}) + "\n")
    t = _time.time()
    os.utime(a, (t - 100, t - 100))
    os.utime(b, (t, t))
    meta, _, verdicts = read_linkmap([str(a), str(b)])
    assert meta["job_id"] == "y"
    assert [v["verdict"] for v in verdicts] == ["slow"]
    assert "replaying the newest (job y)" in capsys.readouterr().err
    # one FILE with disagreeing metas is still a garbage join
    c = tmp_path / "linkmap-u-0-c.log"
    c.write_text(json.dumps({"record": "meta", "job_id": "z", "n": 2}) + "\n"
                 + json.dumps({"record": "meta", "job_id": "z", "n": 8})
                 + "\n")
    with pytest.raises(ValueError, match="disagreeing meta records"):
        read_linkmap([str(c)])
    with pytest.raises(ValueError, match="no meta record"):
        read_linkmap([])


# --- synthetic prober ---------------------------------------------------


def _prober(faults=(), seed=7, **kw):
    from tpu_perf.faults import FaultInjector

    inj = FaultInjector(list(faults), seed=seed, synthetic_s=1e-3)
    kw.setdefault("nbytes", 65536)
    kw.setdefault("iters", 2)
    kw.setdefault("runs", 3)
    return LinkProber(None, injector=inj, n_devices=8, **kw)


def test_synthetic_prober_fills_every_link_deterministically():
    plan = plan_mesh_links((2, 4))
    a = _prober().probe(plan)
    b = _prober().probe(plan)
    assert len(a.probes) == 24
    assert all(len(r.samples) == 3 for r in a.probes)
    assert [r.samples for r in a.probes] == [r.samples for r in b.probes]
    c = _prober(seed=8).probe(plan)
    assert [r.samples for r in a.probes] != [r.samples for r in c.probes]
    m = a.latency_matrix()
    probed = sum(1 for row in m for cell in row if cell is not None)
    assert probed == 24
    # per-message seconds: whole-run mean / iters
    r0 = a.probes[0]
    assert m[r0.probe.src][r0.probe.dst] == pytest.approx(
        sum(r0.samples) / 3 / 2)


def test_synthetic_prober_requires_shape_knowledge():
    with pytest.raises(ValueError, match="n_devices"):
        from tpu_perf.faults import FaultInjector

        LinkProber(None, injector=FaultInjector([], synthetic_s=1e-3),
                   nbytes=1024)
    with pytest.raises(ValueError, match="mesh is required"):
        LinkProber(None, nbytes=1024, n_devices=8)
    with pytest.raises(ValueError, match="fence"):
        _prober(fence="slope")


def test_rank_and_op_targeted_fault_localizes(tmp_path):
    """The acceptance contract: a rank-targeted fault on one link's op
    degrades exactly that probe stream, and grading localizes it."""
    from tpu_perf.faults import FaultSpec

    target = probe_op_name((1, 2), (1, 3))
    plan = plan_mesh_links((2, 4))
    result = _prober(
        faults=[FaultSpec(kind="delay", op=target, rank=0, magnitude=3.0)],
    ).probe(plan)
    verdicts = grade(result)
    sick = [v for v in verdicts if v.verdict != "ok"]
    assert [(v.op, v.verdict, v.rank) for v in sick] == [(target, "slow", 0)]
    # a fault filtered to a rank no probe belongs to never fires
    result = _prober(
        faults=[FaultSpec(kind="delay", op=target, rank=3, magnitude=3.0)],
    ).probe(plan)
    assert all(v.verdict == "ok" for v in grade(result))


def test_probe_nbytes_rounding_is_consistent_everywhere():
    """The fault matcher, the synthetic series, and the records must all
    see the SAME (dtype-rounded) nbytes — a fault spec built by copying
    nbytes off a probe record must actually fire."""
    from tpu_perf.faults import FaultSpec

    target = probe_op_name((0,), (1,))
    result = _prober(
        faults=[FaultSpec(kind="delay", op=target, nbytes=16,
                          magnitude=3.0)],
        nbytes=9, dtype="float64",  # 9 B rounds up to 2 x 8 = 16
    ).probe(plan_mesh_links((8,)))
    assert result.nbytes == 16
    assert all(r.nbytes == 16 for r in result.probes)
    sick = [v for v in grade(result) if v.verdict != "ok"]
    assert [v.op for v in sick] == [target]


def test_drop_run_fault_makes_link_dead():
    from tpu_perf.faults import FaultSpec

    target = probe_op_name((0, 0), (0, 1))
    result = _prober(faults=[FaultSpec(kind="drop_run", op=target)]).probe(
        plan_mesh_links((2, 4)))
    verdicts = {v.op: v for v in grade(result)}
    assert verdicts[target].verdict == "dead"
    assert sum(1 for v in verdicts.values() if v.verdict != "ok") == 1


# --- real probes on the virtual mesh ------------------------------------


def test_real_probe_smoke(eight_devices):
    """Real ppermute probes on the 8-device CPU mesh: every link gets a
    sample (CPU timing noise is not under test — thresholds parked)."""
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh((2, 4), ("a", "b"))
    prober = LinkProber(mesh, nbytes=1024, iters=1, runs=1)
    result = prober.probe(plan_mesh_links((2, 4), ("a", "b")))
    assert len(result.probes) == 24
    assert all(r.samples and r.samples[0] > 0 for r in result.probes)
    cfg = GradeConfig(mad_z=1e9, rel_threshold=1e6, dead_ratio=1e9)
    assert all(v.verdict == "ok" for v in grade(result, cfg))


def test_real_probe_concurrent_schedules(eight_devices):
    from tpu_perf.parallel import make_mesh

    mesh = make_mesh((8,), ("x",))
    prober = LinkProber(mesh, nbytes=1024, iters=1, runs=2)
    result = prober.probe(plan_mesh_links((8,), ("x",)), concurrent=True)
    assert result.concurrent
    assert len(result.probes) == 16
    # one batch time is attributed to every probe of its schedule
    by_sched: dict[int, set] = {}
    for r in result.probes:
        by_sched.setdefault(r.probe.shift, set()).add(tuple(r.samples))
    assert all(len(v) == 1 for v in by_sched.values())


# --- CLI end to end -----------------------------------------------------


def _run_linkmap(tmp_path, capsys, *extra, expect):
    args = ["linkmap", "--mesh", "2x4", "--synthetic", "0.001", "--seed",
            "7", "-b", "64K", "-l", str(tmp_path / "logs"), *extra]
    rc = main(args)
    out = capsys.readouterr().out
    assert rc == expect, out
    return out


def test_cli_clean_sweep_all_ok(tmp_path, capsys):
    out = _run_linkmap(tmp_path, capsys, expect=0)
    assert "all 24 link(s) ok." in out
    assert "src\\dst" in out  # the heatmap rendered
    # records landed as ONE finished linkmap file; no health events fired
    logs = list((tmp_path / "logs").glob("linkmap-*.log"))
    assert len(logs) == 1
    assert not list((tmp_path / "logs").glob("health-*.log"))
    records = [json.loads(ln) for ln in logs[0].read_text().splitlines()]
    kinds = {r["record"] for r in records}
    assert kinds == {"meta", "probe", "verdict"}
    assert sum(1 for r in records if r["record"] == "probe") == 24


def test_cli_localizes_rank_targeted_fault(tmp_path, capsys):
    """ISSUE 3 acceptance: the injected link — and only it — grades
    non-ok, with device coordinates and rank named in the verdict AND
    in the resulting link_degraded health event; exit 6."""
    spec = tmp_path / "fault.json"
    spec.write_text(json.dumps({"faults": [
        {"kind": "spike", "op": "link:(1,2)>(1,3)", "rank": 0,
         "magnitude": 30.0},
    ]}))
    out = _run_linkmap(tmp_path, capsys, "--faults", str(spec), expect=6)
    assert "23 ok, 1 slow, 0 dead" in out
    assert "link:(1,2)>(1,3) slow (rank 0" in out
    (ev_log,) = (tmp_path / "logs").glob("health-*.log")
    events = [json.loads(ln) for ln in ev_log.read_text().splitlines()]
    assert [(e["kind"], e["op"], e["rank"]) for e in events] == \
        [("link_degraded", "link:(1,2)>(1,3)", 0)]
    assert events[0]["severity"] == "warning"
    # replay renders the same verdict from the durable records, exit 6
    capsys.readouterr()
    rc = main(["linkmap", "report", str(tmp_path / "logs")])
    out = capsys.readouterr().out
    assert rc == 6
    assert "23 ok, 1 slow, 0 dead" in out and "link:(1,2)>(1,3)" in out


def test_cli_dead_link_event_is_critical(tmp_path, capsys):
    spec = tmp_path / "fault.json"
    spec.write_text(json.dumps({"faults": [
        {"kind": "drop_run", "op": "link:(0,1)>(0,2)"},
    ]}))
    out = _run_linkmap(tmp_path, capsys, "--faults", str(spec), expect=6)
    assert "1 dead" in out
    (ev_log,) = (tmp_path / "logs").glob("health-*.log")
    (event,) = [json.loads(ln) for ln in ev_log.read_text().splitlines()]
    assert event["severity"] == "critical"
    assert event["kind"] == "link_degraded"


def test_cli_json_artifact(tmp_path, capsys):
    out = _run_linkmap(tmp_path, capsys, "--format", "json", expect=0)
    data = json.loads(out)
    assert data["meta"]["n"] == 8 and data["meta"]["synthetic"] is True
    # every grading knob in the meta, so a record consumer can tell a
    # threshold change from a link change
    assert data["meta"]["roofline_floor"] == 0.5
    assert data["meta"]["mad_z"] == 6.0
    assert len(data["probes"]) == 24 and len(data["verdicts"]) == 24
    assert {v["verdict"] for v in data["verdicts"]} == {"ok"}
    # json replay too
    capsys.readouterr()
    assert main(["linkmap", "report", str(tmp_path / "logs"),
                 "--format", "json"]) == 0
    replay = json.loads(capsys.readouterr().out)
    assert replay["meta"] == data["meta"]
    assert replay["verdicts"] == data["verdicts"]


def test_cli_synthetic_requires_mesh(capsys):
    rc = main(["linkmap", "--synthetic", "0.001"])
    assert rc == 2
    assert "--mesh" in capsys.readouterr().err


def test_cli_rejects_negative_roofline(capsys):
    # only 0 is the documented "disable": a negative typo must not
    # silently turn the roofline gate off
    rc = main(["linkmap", "--mesh", "2x4", "--synthetic", "0.001",
               "--roofline-gbps", "-5"])
    assert rc == 2
    assert "--roofline-gbps" in capsys.readouterr().err


def test_cli_report_no_logs(tmp_path, capsys):
    rc = main(["linkmap", "report", str(tmp_path)])
    assert rc == 1
    assert "no linkmap logs" in capsys.readouterr().err


def test_cli_report_refuses_verdictless_sweep(tmp_path, capsys):
    """A sweep killed before grading leaves meta/probe rows only: the
    replay must NOT pass the sick-link gate on a sweep that graded
    nothing."""
    (tmp_path / "linkmap-u-0-a.log.open").write_text(
        json.dumps({"record": "meta", "job_id": "x", "n": 8}) + "\n"
        + json.dumps({"record": "probe", "src": 0, "dst": 1}) + "\n")
    rc = main(["linkmap", "report", str(tmp_path)])
    assert rc == 1
    assert "no verdict records" in capsys.readouterr().err


def test_cli_all_pairs_synthetic(tmp_path, capsys):
    out = _run_linkmap(tmp_path, capsys, "--all-pairs", expect=0)
    assert "all 56 link(s) ok." in out  # 8*7 ordered pairs


def test_cli_inline_fault_spells_link_ops(tmp_path, capsys):
    """The inline --fault spelling must be able to target a link op even
    though the op name carries a colon of its own."""
    out = _run_linkmap(tmp_path, capsys, "--fault",
                       "spike:link:(1,2)>(1,3):0:1-:30", expect=6)
    assert "link:(1,2)>(1,3) slow (rank 0" in out


def test_cli_synthetic_concurrent_records_serial(tmp_path, capsys):
    """--concurrent has no batch to time in synthetic mode: the sweep is
    the exact serial measurement and the durable meta must say so (a
    concurrent=true record marks per-link values as batch upper
    bounds)."""
    out = _run_linkmap(tmp_path, capsys, "--concurrent", "--format",
                       "json", expect=0)
    assert json.loads(out)["meta"]["concurrent"] is False


def test_cli_multi_sweep_folder_replays_newest(tmp_path, capsys):
    _run_linkmap(tmp_path, capsys, expect=0)
    spec = tmp_path / "fault.json"
    spec.write_text(json.dumps({"faults": [
        {"kind": "drop_run", "op": "link:(0,1)>(0,2)"},
    ]}))
    _run_linkmap(tmp_path, capsys, "--faults", str(spec), expect=6)
    logs = sorted((tmp_path / "logs").glob("linkmap-*.log"),
                  key=lambda p: p.stat().st_mtime)
    assert len(logs) == 2
    import os
    import time as _time

    t = _time.time()  # same-second sweeps: force distinct mtimes
    os.utime(logs[0], (t - 100, t - 100))
    os.utime(logs[1], (t, t))
    rc = main(["linkmap", "report", str(tmp_path / "logs")])
    cap = capsys.readouterr()
    assert rc == 6  # the newest (faulted) sweep is the one replayed
    assert "1 dead" in cap.out
    assert "2 linkmap sweeps found; replaying the newest" in cap.err


# --- ingest routing -----------------------------------------------------


def test_linkmap_family_rides_ingest_with_no_newest_skip(tmp_path):
    from tpu_perf.ingest.pipeline import run_all_ingest_passes

    class Spy:
        def __init__(self):
            self.paths = []

        def ingest(self, path):
            self.paths.append(path)

    (tmp_path / "linkmap-u-0-a.log").write_text('{"record": "meta"}\n')
    (tmp_path / "linkmap-u-0-b.log.open").write_text('{"record": "meta"}\n')
    spy = Spy()
    n = run_all_ingest_passes(str(tmp_path), skip_newest=5, backend=spy)
    # the finished file ingests despite skip_newest (lazy family:
    # .open marks the active file, so no newest-N heuristic applies)
    assert n == 1
    assert [p.split("/")[-1] for p in spy.paths] == ["linkmap-u-0-a.log"]
    assert (tmp_path / "linkmap-u-0-b.log.open").exists()


def test_kusto_routing_names_linkmap_table():
    # the routing contract without the azure SDK: table constants exist
    # and each JSONL family is distinct (eight families total since the
    # tuner-selection family joined)
    from tpu_perf.ingest import pipeline as pl
    from tpu_perf.schema import (
        ALL_PREFIXES, FLEET_PREFIX, LINKMAP_PREFIX, SPANS_PREFIX,
        TUNE_PREFIX,
    )

    assert LINKMAP_PREFIX in ALL_PREFIXES and SPANS_PREFIX in ALL_PREFIXES
    assert FLEET_PREFIX in ALL_PREFIXES and TUNE_PREFIX in ALL_PREFIXES
    assert len(ALL_PREFIXES) == 8
    assert pl.LINKMAP_TABLE == "LinkMapTPU"
    assert pl.SPANS_TABLE == "SpanEventsTPU"
    assert pl.FLEET_TABLE == "FleetRollupTPU"
    assert pl.TUNE_TABLE == "TuneSelectionTPU"
    assert len({pl.TPU_TABLE, pl.HEALTH_TABLE, pl.CHAOS_TABLE,
                pl.LINKMAP_TABLE, pl.SPANS_TABLE, pl.FLEET_TABLE,
                pl.TUNE_TABLE}) == 7
