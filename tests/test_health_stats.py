"""Streaming estimators (tpu_perf.health.stats): correctness against
exact batch computations, no sample retention assumed."""

import math
import statistics

import pytest

from tpu_perf.health.stats import EWMA, P2Quantile, PointBaseline, Welford
from tpu_perf.metrics import percentile


def _series(n, scale=1.0, offset=1.0):
    """Deterministic pseudo-noise (no RNG: reproducible across runs)."""
    return [offset + scale * (math.sin(i * 12.9898) * 0.5 + 0.5)
            for i in range(n)]


def test_welford_matches_batch_stats():
    xs = _series(500)
    w = Welford()
    for x in xs:
        w.push(x)
    assert w.n == 500
    assert w.mean == pytest.approx(statistics.fmean(xs), rel=1e-12)
    assert w.variance() == pytest.approx(statistics.variance(xs), rel=1e-9)
    assert w.std() == pytest.approx(statistics.stdev(xs), rel=1e-9)


def test_welford_degenerate():
    w = Welford()
    assert w.variance() == 0.0
    w.push(3.0)
    assert w.mean == 3.0 and w.variance() == 0.0 and w.std() == 0.0


def test_ewma_seeds_and_converges():
    e = EWMA(alpha=0.3)
    assert e.value is None
    e.push(1.0)
    assert e.value == 1.0
    e.push(2.0)
    assert e.value == pytest.approx(0.3 * 2.0 + 0.7 * 1.0)
    for _ in range(100):
        e.push(5.0)
    assert e.value == pytest.approx(5.0, rel=1e-6)


def test_ewma_alpha_validation():
    with pytest.raises(ValueError):
        EWMA(alpha=0.0)
    with pytest.raises(ValueError):
        EWMA(alpha=1.5)


def test_p2_small_sample_is_exact():
    q = P2Quantile(0.5)
    assert q.value() is None
    for x in (5.0, 1.0, 3.0):
        q.push(x)
    # below five samples the exact interpolated percentile is returned
    assert q.value() == percentile([5.0, 1.0, 3.0], 50)


def test_p2_median_tracks_batch_percentile():
    xs = _series(1000)
    q = P2Quantile(0.5)
    for x in xs:
        q.push(x)
    assert q.count == 1000
    assert q.value() == pytest.approx(percentile(xs, 50), rel=0.05)


def test_p2_p99_tracks_batch_percentile():
    xs = _series(2000)
    q = P2Quantile(0.99)
    for x in xs:
        q.push(x)
    # the tail estimate is coarser than the median but must be in the
    # right neighbourhood of the distribution's top
    assert q.value() == pytest.approx(percentile(xs, 99), rel=0.1)


def test_p2_markers_stay_sorted():
    q = P2Quantile(0.5)
    for x in _series(300, scale=10.0):
        q.push(x)
        if q._h is not None:
            assert q._h == sorted(q._h)


def test_p2_constant_series():
    q = P2Quantile(0.5)
    for _ in range(100):
        q.push(2.5)
    assert q.value() == 2.5


def test_p2_quantile_validation():
    with pytest.raises(ValueError):
        P2Quantile(0.0)
    with pytest.raises(ValueError):
        P2Quantile(1.0)


def test_point_baseline_warmup_gating():
    b = PointBaseline(warmup=10)
    for i in range(9):
        b.update(1.0 + i * 1e-6)
        assert not b.ready
    b.update(1.0)
    assert b.ready and b.n == 10


def test_point_baseline_flat_run_counts_identical_samples():
    # flat_run is the LENGTH of the identical run: N bit-identical
    # samples read as flat_run == N, so the flatline knob means what it
    # says ("N consecutive identical samples = stuck")
    b = PointBaseline(warmup=1)
    b.update(1.0)
    assert b.flat_run == 1
    for i in range(5):
        b.update(1.0)
        assert b.flat_run == i + 2
    b.update(1.1)  # movement re-arms the counter
    assert b.flat_run == 1


def test_point_baseline_validation():
    with pytest.raises(ValueError):
        PointBaseline(warmup=0)
