"""Model-step scenario engine (ISSUE 15): v-variant numerics vs NumPy
references at imbalance ratios {1, 2, 8} on 1D and 2D meshes, int32
bit-exact allgatherv, the lockstep proof under imbalance, the
declarative spec/composition layer, the imbalance sweep axis end to
end, the decorated-label round trip (satellite 2), and the hier
mixed-inner registry grammar (satellite 1)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from tpu_perf.config import Options
from tpu_perf.schema import (
    RESULT_HEADER, ResultRow, base_op, decorate_op, parse_op_label,
    timestamp_now,
)
from tpu_perf.scenarios import vops
from tpu_perf.scenarios.spec import (
    BUILTIN_SCENARIOS, PhaseSpec, ScenarioSpec, load_scenario,
    resolve_scenarios, scenario_from_json,
)
from tpu_perf.sweep import parse_imbalance


# ------------------------------------------------ counts & validation


def test_imbalance_weights():
    assert vops.imbalance_weights(8, 1) == (1,) * 8
    assert vops.imbalance_weights(8, 8) == (1,) * 7 + (8,)
    assert vops.imbalance_weights(1, 4) == (4,)
    with pytest.raises(ValueError, match="integer >= 1"):
        vops.imbalance_weights(8, 0)
    with pytest.raises(ValueError, match="integer >= 1"):
        vops.imbalance_weights(8, 1.5)


def test_v_counts_semantics():
    # allgatherv: nbytes is the gathered total; shard = the max count
    counts, offsets, elems, actual = vops.v_counts(
        "allgatherv", 44 * 4, 8, 4, 2)
    assert sum(counts) * 4 == actual
    assert max(counts) == elems and counts[-1] == 2 * counts[0]
    assert offsets == tuple(sum(counts[:r]) for r in range(8))
    # reduce_scatter_v: nbytes is the per-device input buffer
    counts, _, elems, actual = vops.v_counts(
        "reduce_scatter_v", 50 * 4, 8, 4, 8)
    assert elems == sum(counts) and elems * 4 == actual
    with pytest.raises(ValueError, match="not a v-variant"):
        vops.v_counts("allreduce", 64, 8, 4, 1)


def test_parse_imbalance():
    assert parse_imbalance("1,2,8") == (1, 2, 8)
    assert parse_imbalance("4") == (4,)
    with pytest.raises(ValueError, match="integers >= 1"):
        parse_imbalance("0,2")
    with pytest.raises(ValueError, match="integers >= 1"):
        parse_imbalance("2x")
    with pytest.raises(ValueError, match="empty"):
        parse_imbalance(",")


# ------------------------------------- numerics vs NumPy (satellite 3)


def _mesh(shape=(), axes=()):
    from tpu_perf.parallel import make_mesh

    return make_mesh(shape, axes)


def _host_shards(built):
    """The example input's per-device shards, in flat device order."""
    x = np.asarray(built.example_input)
    n = built.n_devices
    return x.reshape(n, -1)


def _step_out(built):
    import jax

    return np.asarray(
        jax.block_until_ready(built.step(built.example_input))
    ).reshape(built.n_devices, -1)


def _expected_gatherv(shards, counts, offsets, elems):
    gathered = np.concatenate(
        [shards[r][: counts[r]] for r in range(len(counts))])
    return np.stack([gathered[offsets[d]: offsets[d] + elems]
                     for d in range(len(counts))])


@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_allgatherv_matches_numpy(eight_devices, ratio):
    from tpu_perf.ops import build_op

    mesh = _mesh()
    built = build_op("allgatherv", mesh, 4 * 44, 2, imbalance=ratio)
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, ratio)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    # chained iterations are a fixed point (the carry's own block is
    # preserved bit-exactly), so iters=2 must equal one application
    np.testing.assert_array_equal(_step_out(built), want)
    assert built.imbalance == ratio


@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_allgatherv_matches_numpy_on_2d_mesh(eight_devices, ratio):
    # a 2D (2, 4) mesh with the collective over the named inner axis:
    # each row of the mesh gathers independently over its 4 devices
    from tpu_perf.ops import build_op

    mesh = _mesh((2, 4), ("a", "b"))
    built = build_op("allgatherv", mesh, 4 * 20, 1, axis="b",
                     imbalance=ratio)
    assert built.n_devices == 4
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 20, 4, 4, ratio)
    # the example buffer is sharded over the NAMED axis only (each
    # mesh row sees the same four shards), so both rows' gathers agree
    shards = _host_shards(built)
    want = _expected_gatherv(shards, counts, offsets, elems)
    np.testing.assert_array_equal(_step_out(built), want)


def test_allgatherv_int32_bit_exact(eight_devices):
    # pure movement: integer payloads round-trip bit for bit
    from tpu_perf.ops import build_op

    built = build_op("allgatherv", _mesh(), 4 * 44, 2, dtype="int32",
                     imbalance=8)
    counts, offsets, elems, _ = vops.v_counts(
        "allgatherv", 4 * 44, 8, 4, 8)
    want = _expected_gatherv(_host_shards(built), counts, offsets, elems)
    out = _step_out(built)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, want)


@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_reduce_scatter_v_matches_numpy(eight_devices, ratio):
    from tpu_perf.ops import build_op

    mesh = _mesh()
    built = build_op("reduce_scatter_v", mesh, 4 * 50, 1,
                     imbalance=ratio)
    counts, offsets, elems, _ = vops.v_counts(
        "reduce_scatter_v", 4 * 50, 8, 4, ratio)
    shards = _host_shards(built).astype(np.float64)
    out = _step_out(built)
    mean = shards.mean(axis=0)
    for d in range(8):
        want = shards[d].copy()
        o, c = offsets[d], counts[d]
        want[o:o + c] = mean[o:o + c]
        np.testing.assert_allclose(out[d], want, rtol=1e-6,
                                   err_msg=f"dev {d}")


@pytest.mark.parametrize("ratio", [1, 2, 8])
def test_reduce_scatter_v_matches_numpy_on_2d_mesh(eight_devices, ratio):
    from tpu_perf.ops import build_op

    mesh = _mesh((2, 4), ("a", "b"))
    built = build_op("reduce_scatter_v", mesh, 4 * 24, 1, axis="b",
                     imbalance=ratio)
    counts, offsets, _, _ = vops.v_counts(
        "reduce_scatter_v", 4 * 24, 4, 4, ratio)
    shards = _host_shards(built).astype(np.float64)
    out = _step_out(built)
    mean = shards.mean(axis=0)
    for d in range(4):
        want = shards[d].copy()
        o, c = offsets[d], counts[d]
        want[o:o + c] = mean[o:o + c]
        np.testing.assert_allclose(out[d], want, rtol=1e-6)


def test_a2av_dispatch_combine_round_trip(eight_devices):
    # the MoE pair: combine returns every dispatched block to its
    # source — the valid region round-trips bit for bit
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_perf.compat import shard_map

    mesh = _mesh()
    n, k = 8, 64
    blocks, roffs = vops.a2av_layout(k, n, 4)
    assert blocks[-1] == 4 * blocks[0]

    def disp(x):
        return vops.a2av(x, "x", n, blocks, roffs)

    def comb(x):
        return vops.a2av(x, "x", n, blocks, roffs, inverse=True)

    x = np.arange(n * k, dtype=np.float32).reshape(n, k) + 1.0
    sharding = NamedSharding(mesh.jax_mesh if hasattr(mesh, "jax_mesh")
                             else mesh, P(mesh.axis_names))
    xg = jax.device_put(jnp.asarray(x.reshape(-1)), sharding)
    gd = jax.jit(shard_map(disp, mesh=mesh, in_specs=P(mesh.axis_names),
                           out_specs=P(mesh.axis_names)))
    gc = jax.jit(shard_map(comb, mesh=mesh, in_specs=P(mesh.axis_names),
                           out_specs=P(mesh.axis_names)))
    mid = jax.block_until_ready(gd(xg))
    midh = np.asarray(mid).reshape(n, k)
    for d in range(n):
        for r in range(n):
            b = blocks[r]
            np.testing.assert_array_equal(
                midh[d][roffs[r]: roffs[r] + b],
                x[r][d * b: (d + 1) * b])
    back = np.asarray(jax.block_until_ready(gc(mid))).reshape(n, k)
    for r in range(n):
        np.testing.assert_array_equal(back[r][: n * blocks[r]],
                                      x[r][: n * blocks[r]])


# --------------------------------------- lockstep proof (satellite 3)


def test_vop_schedule_is_one_program_with_static_collective_order(
        eight_devices):
    """The R2 proof as geometry: a v-variant kernel is ONE SPMD program
    whose ppermute count derives only from the static (n, ratio) pair —
    per round, origins group by block size, so the traced program
    contains exactly (n-1) * len(groups) collectives for gatherv and
    (n-1) * len(groups) + seeding for reduce_scatter_v, with no
    rank-dependent control flow anywhere (every rank enters every
    collective; selection is where/dynamic_slice)."""
    import jax

    from tpu_perf.ops import build_op

    for op, ratio in (("allgatherv", 8), ("allgatherv", 1),
                      ("reduce_scatter_v", 8)):
        built = build_op(op, _mesh(), 4 * 44, 1, imbalance=ratio)
        jaxpr = jax.make_jaxpr(built.step)(built.example_input)
        text = str(jaxpr)
        counts, _, _, _ = vops.v_counts(op, 4 * 44, 8, 4, ratio)
        groups = len({c for c in counts})
        assert text.count("ppermute") == 7 * groups, (op, ratio)
        # no rank-dependent control flow: the only conditionals are
        # data selects, never cond/while on axis_index
        assert "cond[" not in text and "while[" not in text


def test_two_simulated_ranks_agree_on_run_stream_under_imbalance(
        eight_devices, tmp_path):
    """The PR-11 lockstep pattern at the driver level: the same
    imbalanced plan executed twice (two 'ranks' of a reproduced job)
    yields byte-identical row streams modulo timing/timestamps — same
    points, same order, same imbalance coordinates, same run counts —
    because the plan and the schedule derive only from static
    coordinates, never from rank-local state."""
    from tpu_perf.cli import main

    streams = []
    for rank in ("a", "b"):
        log = tmp_path / rank
        assert main(["run", "--op", "allgatherv", "--imbalance", "1,8",
                     "-b", "4K", "-i", "1", "-r", "2", "-l", str(log)]) == 0
        rows = []
        for p in sorted(log.glob("tpu-*.log")):
            rows += [ResultRow.from_csv(ln)
                     for ln in p.read_text().splitlines()]
        streams.append([(r.op, r.nbytes, r.run_id, r.imbalance)
                        for r in rows])
    assert streams[0] == streams[1]
    assert {i for _, _, _, i in streams[0]} == {1, 8}


# ------------------------------------------------- build_op validation


def test_build_op_v_validation(eight_devices):
    from tpu_perf.ops import build_op

    mesh = _mesh()
    with pytest.raises(ValueError, match="no uneven-payload schedule"):
        build_op("allreduce", mesh, 4096, 2, imbalance=2)
    with pytest.raises(ValueError, match="integer >= 1"):
        build_op("allgatherv", mesh, 4096, 2, imbalance=0)
    # v-ops race through their own registry (tpu_perf.arena.valgos):
    # a balanced-catalog name the v-side lacks names the v-catalog
    with pytest.raises(ValueError, match="v-decomposition"):
        build_op("allgatherv", mesh, 4096, 2, algo="ring")
    # a flat v-schedule still needs one axis (native spans the mesh)
    with pytest.raises(ValueError, match="single mesh axis"):
        build_op("allgatherv", _mesh((2, 4), ("a", "b")), 4096, 2,
                 algo="sortring")
    with pytest.raises(ValueError, match="float dtype"):
        build_op("reduce_scatter_v", mesh, 4096, 2, dtype="int32")
    with pytest.raises(ValueError, match="unknown op"):
        build_op("allgathervv", mesh, 4096, 2)


def test_compile_spec_keys_on_imbalance():
    from tpu_perf.compilepipe import CompileSpec

    a = CompileSpec.make("allgatherv", 1024, 10, imbalance=1)
    b = CompileSpec.make("allgatherv", 1024, 10, imbalance=8)
    assert a != b and len({a, b}) == 2
    assert CompileSpec.make("ring", 8, 10).imbalance == 1


# ------------------------------------------------ spec layer


def test_builtin_scenarios_shape():
    assert set(BUILTIN_SCENARIOS) == {
        "tp-allreduce-burst", "moe-dispatch-combine", "pipeline-chain"}
    assert BUILTIN_SCENARIOS["moe-dispatch-combine"].uses_imbalance
    assert not BUILTIN_SCENARIOS["tp-allreduce-burst"].uses_imbalance
    burst = BUILTIN_SCENARIOS["tp-allreduce-burst"]
    assert burst.phases[0].repeat == 4 and burst.phases[0].op == "allreduce"


def test_phase_spec_validation():
    with pytest.raises(ValueError, match="unknown scenario phase op"):
        PhaseSpec(op="matmul")
    with pytest.raises(ValueError, match="repeat"):
        PhaseSpec(op="allreduce", repeat=0)
    with pytest.raises(ValueError, match="size_frac"):
        PhaseSpec(op="allreduce", size_frac=0.0)
    with pytest.raises(ValueError, match="inverse"):
        PhaseSpec(op="allreduce", inverse=True)


def test_scenario_spec_validation():
    with pytest.raises(ValueError, match="delimiter"):
        ScenarioSpec(name="bad[name]", phases=(PhaseSpec(op="ppermute"),))
    with pytest.raises(ValueError, match="no phases"):
        ScenarioSpec(name="empty", phases=())
    with pytest.raises(ValueError, match="not be empty"):
        ScenarioSpec(name="", phases=(PhaseSpec(op="ppermute"),))


def test_scenario_json_round_trip(tmp_path):
    data = {"name": "my-step", "summary": "two-phase",
            "phases": [{"op": "allreduce", "repeat": 2},
                       {"op": "all_to_all_v", "inverse": True,
                        "size_frac": 0.5}]}
    spec = scenario_from_json(data)
    assert spec.name == "my-step" and spec.phases[1].inverse
    assert spec.phases[1].size_frac == 0.5
    path = tmp_path / "my.json"
    path.write_text(json.dumps(data))
    assert load_scenario(str(path)) == spec
    with pytest.raises(ValueError, match="unknown key"):
        scenario_from_json({"name": "x",
                            "phases": [{"op": "allreduce", "ops": 1}]})
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    with pytest.raises(ValueError, match="bad scenario spec"):
        load_scenario(str(bad))


def test_resolve_scenarios(tmp_path):
    specs = resolve_scenarios(["tp-allreduce-burst"])
    assert specs[0] is BUILTIN_SCENARIOS["tp-allreduce-burst"]
    # idempotent: specs pass through (the dataclasses.replace contract)
    assert resolve_scenarios(specs) == specs
    with pytest.raises(ValueError, match="unknown scenario"):
        resolve_scenarios(["nope"])
    with pytest.raises(ValueError, match="named twice"):
        resolve_scenarios(["pipeline-chain", "pipeline-chain"])
    path = tmp_path / "c.json"
    path.write_text(json.dumps({"name": "custom",
                                "phases": [{"op": "ppermute"}]}))
    assert resolve_scenarios([str(path)])[0].name == "custom"


# ---------------------------------------------- composition layer


def test_scenario_labels_round_trip():
    from tpu_perf.scenarios.compose import (
        scenario_algo_label, spec_for_label, split_scenario_label,
    )

    spec = BUILTIN_SCENARIOS["moe-dispatch-combine"]
    assert scenario_algo_label(spec) == "moe-dispatch-combine"
    lbl = scenario_algo_label(spec, "ring")
    assert lbl == "moe-dispatch-combine+ring"
    assert split_scenario_label(lbl) == ("moe-dispatch-combine", "ring")
    assert split_scenario_label("x") == ("x", "native")
    assert spec_for_label((spec,), lbl) is spec
    with pytest.raises(ValueError, match="no scenario named"):
        spec_for_label((spec,), "other")


def test_scenario_algos_for_validation():
    from tpu_perf.scenarios.compose import scenario_algos_for

    specs = resolve_scenarios(["tp-allreduce-burst", "pipeline-chain"])

    class O:
        scenario = specs

    import io

    O.algo = "native"
    assert scenario_algos_for(O) == ["tp-allreduce-burst",
                                     "pipeline-chain"]
    # an inner covering only SOME scenarios relabels the uncovered
    # ones to their bare native label, loudly (pipeline-chain is all
    # ppermute — ring changes nothing there) — never a +inner label on
    # a byte-identical native composition
    O.algo = "ring"
    note = io.StringIO()
    assert scenario_algos_for(O, err=note) == ["tp-allreduce-burst+ring",
                                               "pipeline-chain"]
    assert "no phase with a registered 'ring'" in note.getvalue()
    for bad, msg in (("all", "ONE per-phase inner"),
                     ("ring,bruck", "ONE per-phase inner"),
                     ("hier", "hierarchical"),
                     ("nope", "unknown scenario inner")):
        O.algo = bad
        with pytest.raises(ValueError, match=msg):
            scenario_algos_for(O)
    # an inner covering NO selected scenario is a hard error
    class P:
        scenario = resolve_scenarios(["pipeline-chain"])
        algo = "ring"

    with pytest.raises(ValueError, match="covers no phase"):
        scenario_algos_for(P)
    # a pow2-only inner fails at PLAN time on an incompatible device
    # count (before any kernel has run), and passes on a pow2 one
    O.algo = "rhd"
    with pytest.raises(ValueError, match="power-of-two"):
        scenario_algos_for(O, 6)
    note = io.StringIO()
    assert scenario_algos_for(O, 8, err=note) == \
        ["tp-allreduce-burst+rhd", "pipeline-chain"]


def test_build_scenario_rejects_uncovered_inner(eight_devices):
    # direct-API misuse: an inner that changes nothing must never
    # compile under a +inner label (the plan layer relabels loudly)
    from tpu_perf.scenarios.compose import build_scenario_op

    moe = BUILTIN_SCENARIOS["moe-dispatch-combine"]
    with pytest.raises(ValueError, match="no phase with a registered"):
        build_scenario_op(moe, _mesh(), 4096, 1, inner="ring")


def test_cli_scenario_rejects_conflicting_explicit_op(capsys):
    # the loud-inert-knob contract: `scenario NAME --op other` must
    # never silently discard the explicit op
    from tpu_perf.cli import main

    assert main(["scenario", "pipeline-chain", "--op", "allreduce",
                 "-b", "4K", "-r", "1"]) == 2
    assert "conflicts with a scenario selection" in \
        capsys.readouterr().err


def test_tp_allreduce_burst_numerics(eight_devices):
    # L chained allreduces of the mean: after the first, every device
    # holds the global mean; the burst is a fixed point thereafter
    from tpu_perf.scenarios.compose import build_scenario_op

    spec = BUILTIN_SCENARIOS["tp-allreduce-burst"]
    built = build_scenario_op(spec, _mesh(), 4 * 64, 2)
    assert built.name == "scenario" and built.algo == "tp-allreduce-burst"
    shards = _host_shards(built).astype(np.float64)
    out = _step_out(built)
    want = np.broadcast_to(shards.mean(axis=0), shards.shape)
    np.testing.assert_allclose(out, want, rtol=1e-6)


@pytest.mark.parametrize("ratio", [1, 4])
def test_moe_dispatch_combine_round_trips_the_buffer(eight_devices, ratio):
    # dispatch followed by combine returns every token to its source:
    # the fused step is data-identity (bit-exact) while the wire moved
    # 2x the routed volume — the honest MoE round trip
    from tpu_perf.scenarios.compose import build_scenario_op

    spec = BUILTIN_SCENARIOS["moe-dispatch-combine"]
    built = build_scenario_op(spec, _mesh(), 4 * 64, 2, imbalance=ratio)
    k = _host_shards(built).shape[1]
    blocks, _ = vops.a2av_layout(k, 8, ratio)
    out, x = _step_out(built), _host_shards(built)
    for r in range(8):
        # every routed token returned to its source, bit for bit
        np.testing.assert_array_equal(out[r][: 8 * blocks[r]],
                                      x[r][: 8 * blocks[r]])
        # the untouched tail carries through the chain
        tot = sum(blocks)
        np.testing.assert_array_equal(out[r][tot:], x[r][tot:])
    assert built.imbalance == ratio


def test_pipeline_chain_numerics(eight_devices):
    # 4 ring hops shift every shard 4 seats around the ring
    from tpu_perf.scenarios.compose import build_scenario_op

    spec = BUILTIN_SCENARIOS["pipeline-chain"]
    built = build_scenario_op(spec, _mesh(), 4 * 64, 1)
    shards = _host_shards(built)
    out = _step_out(built)
    np.testing.assert_array_equal(out, np.roll(shards, 4, axis=0))


def test_scenario_inner_algo_swaps_registered_phases(eight_devices):
    # --algo ring on tp-allreduce-burst: the ring allreduce computes
    # the same mean within reduction-order tolerance
    from tpu_perf.scenarios.compose import build_scenario_op

    spec = BUILTIN_SCENARIOS["tp-allreduce-burst"]
    native = build_scenario_op(spec, _mesh(), 4 * 64, 1)
    ring = build_scenario_op(spec, _mesh(), 4 * 64, 1, inner="ring")
    assert ring.algo == "tp-allreduce-burst+ring"
    np.testing.assert_allclose(_step_out(ring), _step_out(native),
                               rtol=1e-5)


def test_build_scenario_validation(eight_devices):
    from tpu_perf.scenarios.compose import build_scenario_op

    moe = BUILTIN_SCENARIOS["moe-dispatch-combine"]
    burst = BUILTIN_SCENARIOS["tp-allreduce-burst"]
    with pytest.raises(ValueError, match="one mesh axis"):
        build_scenario_op(moe, _mesh((2, 4), ("a", "b")), 4096, 1)
    with pytest.raises(ValueError, match="no v-variant phase"):
        build_scenario_op(burst, _mesh(), 4096, 1, imbalance=8)
    with pytest.raises(ValueError, match="float dtype"):
        build_scenario_op(burst, _mesh(), 4096, 1, dtype="int32")
    with pytest.raises(ValueError, match="unknown scenario inner"):
        build_scenario_op(burst, _mesh(), 4096, 1, inner="nope")


def test_phase_plan_attribution():
    from tpu_perf.scenarios.compose import phase_plan

    moe = BUILTIN_SCENARIOS["moe-dispatch-combine"]
    plan = phase_plan(moe, 4096, 8, imbalance=8)
    assert len(plan) == 2
    assert abs(sum(e["share"] for e in plan) - 1.0) < 1e-9
    assert plan[0]["share"] == pytest.approx(0.5)
    burst = phase_plan(BUILTIN_SCENARIOS["tp-allreduce-burst"], 4096, 8)
    assert len(burst) == 1 and burst[0]["share"] == 1.0
    assert burst[0]["repeat"] == 4


# ------------------------------------------- Options validation


def test_options_imbalance_validation():
    with pytest.raises(ValueError, match="integers >= 1"):
        Options(op="allgatherv", imbalance=(0,))
    with pytest.raises(ValueError, match="no uneven-payload schedule"):
        Options(op="allreduce", imbalance=(1, 2))
    with pytest.raises(ValueError, match="no uneven-payload schedule"):
        Options(op="allgatherv,allreduce", imbalance=(2,))
    Options(op="allgatherv,reduce_scatter_v", imbalance=(1, 2, 8))


def test_options_scenario_validation():
    with pytest.raises(ValueError, match="op='scenario'"):
        Options(op="allreduce", scenario=("tp-allreduce-burst",))
    with pytest.raises(ValueError, match="needs a scenario selection"):
        Options(op="scenario")
    with pytest.raises(ValueError, match="unknown scenario"):
        Options(op="scenario", scenario=("nope",))
    with pytest.raises(ValueError, match="v-variant phase"):
        Options(op="scenario", scenario=("tp-allreduce-burst",),
                imbalance=(2,))
    opts = Options(op="scenario", scenario=("moe-dispatch-combine",),
                   imbalance=(1, 8))
    assert opts.scenario[0].name == "moe-dispatch-combine"
    with pytest.raises(ValueError, match="backend"):
        Options(op="scenario", scenario=("pipeline-chain",),
                backend="mpi")


def test_run_sweep_rejects_driver_coordinates(eight_devices):
    from tpu_perf.runner import run_sweep

    opts = Options(op="allgatherv", imbalance=(1, 2))
    with pytest.raises(ValueError, match="driver path"):
        list(run_sweep(opts, _mesh()))


# -------------------------- decorated labels (satellite 2 round trip)


def test_decorate_parse_round_trip():
    cases = [
        ("allreduce", "", 0, 1, ""),
        ("allreduce", "ring", 0, 1, ""),
        ("allreduce", "ring", 500, 1, ""),
        ("allgatherv", "", 0, 8, ""),
        ("allgatherv", "", 250, 2, ""),
        ("scenario", "moe-dispatch-combine", 0, 8, ""),
        ("scenario", "tp-allreduce-burst+ring", 1000, 1, ""),
        ("allreduce", "hier-ring/native/bruck:dcn=2+ici=4", 0, 1, ""),
        ("allreduce", "hier:dcn=2+ici=4", 500, 2, ""),
        ("allreduce", "", 0, 1, "hbm_stream"),
        ("allreduce", "ring", 500, 8, "mxu_gemm"),
        ("ppermute", "", 0, 1, "ppermute"),
    ]
    for op, algo, skew, imb, load in cases:
        label = decorate_op(op, algo, skew, imb, load)
        assert parse_op_label(label) == (op, algo, skew, imb, load), label
        assert base_op(label) == op, label
    # undecorated spellings parse to neutral coordinates
    assert parse_op_label("hbm_stream") == ("hbm_stream", "", 0, 1, "")
    assert decorate_op("ring") == "ring"
    assert decorate_op("scenario", "moe-dispatch-combine", 0, 8) == \
        "scenario[moe-dispatch-combine]%8"
    # the load coordinate is appended last, so it strips first and the
    # earlier coordinates parse unchanged under it
    assert decorate_op("allreduce", "ring", 0, 1, "hbm_stream") == \
        "allreduce[ring]&hbm_stream"


def test_conformance_resolves_scenario_and_imbalance_labels():
    # the consumer side of the shared parser: an event keyed on the
    # decorated scenario/imbalance label still matches its raw-op fault
    from tpu_perf.faults.conformance import _event_matches
    from tpu_perf.faults.spec import FaultSpec
    from tpu_perf.health.events import HealthEvent

    f = FaultSpec(kind="spike", op="scenario", start=1, end=9,
                  magnitude=5.0)

    def ev(op):
        return HealthEvent(
            timestamp=timestamp_now(), job_id="j", kind="spike",
            severity="warning", op=op, nbytes=0, dtype="float32",
            run_id=5, window=0, observed=1.0, baseline=0.5,
        )

    assert _event_matches(f, "spike", ev("scenario[moe-dispatch-combine]%8"),
                          1, 9, 0)
    assert _event_matches(f, "spike", ev("scenario[tp-allreduce-burst]"),
                          1, 9, 0)
    assert not _event_matches(f, "spike", ev("allgatherv%8"), 1, 9, 0)


# ------------------------------------------------- rows & report


def _row(**kw):
    base = dict(
        timestamp=timestamp_now(), job_id="j", backend="jax",
        op="allgatherv", nbytes=1024, iters=4, run_id=1, n_devices=8,
        lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.04,
    )
    base.update(kw)
    return ResultRow(**base)


def test_imbalance_row_widths_and_round_trip():
    balanced = _row()
    assert len(balanced.to_csv().split(",")) == 18  # byte-identical
    row = _row(imbalance=8)
    line = row.to_csv()
    assert len(line.split(",")) == 22
    back = ResultRow.from_csv(line)
    assert back.imbalance == 8 and back.skew_us == 0 and back.algo == ""
    # every predecessor width still parses with imbalance defaulting 1
    full = _row(imbalance=8, skew_us=500, algo="a", span_id="s").to_csv()
    for width in (12, 13, 15, 18, 19, 20, 21):
        assert ResultRow.from_csv(
            ",".join(full.split(",")[:width])).imbalance == 1
    # the padded-empty trailer (run --csv rectangularization) parses
    padded = balanced.to_csv() + ",,,0,"
    assert ResultRow.from_csv(padded).imbalance == 1
    assert len(RESULT_HEADER.split(",")) == 18


def test_report_excludes_imbalanced_rows_from_clean_pivots():
    from tpu_perf.report import (
        aggregate, compare, compare_pallas, imbalance_cost,
    )

    rows = []
    for imb in (1, 8):
        for run in (1, 2):
            rows.append(_row(imbalance=imb, run_id=run,
                             lat_us=10.0 * imb,
                             nbytes=1024 + (4 if imb > 1 else 0)))
    points = aggregate(rows)
    assert {p.imbalance for p in points} == {1, 8}
    for cmp in compare(points):
        assert cmp.jax is None or cmp.jax.imbalance == 1
    for cmp in compare_pallas(points):
        assert cmp.xla is None or cmp.xla.imbalance == 1
    cost = imbalance_cost(points)
    assert len(cost) == 1 and cost[0].imbalance == 8
    assert cost[0].base is not None
    assert cost[0].cost == pytest.approx(8.0)


def test_report_scenario_steps_table():
    from tpu_perf.report import (
        aggregate, scenario_steps, scenario_to_markdown,
    )

    rows = []
    for imb, lat in ((1, 100.0), (8, 250.0)):
        for run in (1, 2):
            rows.append(_row(op="scenario", algo="moe-dispatch-combine",
                             imbalance=imb, run_id=run, lat_us=lat,
                             busbw_gbps=0.0, algbw_gbps=0.0))
    rows.append(_row(op="scenario", algo="custom-step", lat_us=50.0))
    steps = scenario_steps(aggregate(rows))
    assert [s.name for s in steps] == ["custom-step",
                                      "moe-dispatch-combine",
                                      "moe-dispatch-combine"]
    moe8 = [s for s in steps if s.imbalance == 8][0]
    assert moe8.cost == pytest.approx(2.5)
    assert moe8.phases is not None and len(moe8.phases) == 2
    custom = [s for s in steps if s.name == "custom-step"][0]
    assert custom.phases is None  # foreign spec: no attribution claim
    md = scenario_to_markdown(steps)
    assert "### " not in md and "moe-dispatch-combine" in md
    assert "all_to_all_v" in md and "—" in md


def test_report_diff_pairs_per_imbalance():
    from tpu_perf.report import aggregate, diff_points

    base = aggregate([_row(imbalance=8, lat_us=10.0),
                      _row(lat_us=10.0)])
    new = aggregate([_row(imbalance=8, lat_us=10.5),
                     _row(lat_us=10.2)])
    diffs = diff_points(base, new)
    assert len(diffs) == 2
    assert {d.imbalance for d in diffs} == {1, 8}
    assert all(d.verdict == "ok" for d in diffs)


def test_report_csv_json_grow_imbalance_only_when_present():
    from tpu_perf.report import aggregate, to_csv, to_json

    clean = aggregate([_row()])
    assert "imbalance" not in to_csv(clean)
    assert "imbalance" not in to_json(clean)
    mixed = aggregate([_row(), _row(imbalance=8, nbytes=1028)])
    csv = to_csv(mixed)
    assert csv.splitlines()[0].endswith(",algo,skew_us,imbalance")
    assert "imbalance" in to_json(mixed)


# -------------------------------------------- driver e2e


def test_imbalance_axis_end_to_end(eight_devices, tmp_path):
    """The acceptance command: rows carry the trailing imbalance
    column, balanced rows keep the pre-imbalance width, report renders
    the imbalance-cost table, and the clean pivots stay balanced."""
    from tpu_perf.cli import main
    from tpu_perf.report import aggregate, compare, imbalance_cost

    log = tmp_path / "axis"
    assert main(["run", "--op", "allgatherv", "--imbalance", "1,2,8",
                 "-b", "4K", "-i", "1", "-r", "2", "-l", str(log)]) == 0
    rows = []
    for p in sorted(log.glob("tpu-*.log")):
        rows += [ResultRow.from_csv(ln)
                 for ln in p.read_text().splitlines()]
    assert {r.imbalance for r in rows} == {1, 2, 8}
    assert all(len(r.to_csv().split(",")) == 18
               for r in rows if r.imbalance == 1)
    assert all(len(r.to_csv().split(",")) == 22
               for r in rows if r.imbalance > 1)
    points = aggregate(rows)
    cost = imbalance_cost(points)
    assert {c.imbalance for c in cost} == {2, 8}
    assert all(c.base is not None for c in cost)
    for cmp in compare(points):
        assert cmp.jax is None or cmp.jax.imbalance == 1


def test_scenario_sweep_end_to_end(eight_devices, tmp_path, capsys):
    """`tpu-perf scenario moe-dispatch-combine` produces ingestible
    scenario rows; report renders the Scenario-steps table with
    per-phase attribution; health/heartbeat key on scenario[...]."""
    from tpu_perf.cli import main

    log = tmp_path / "scn"
    assert main(["scenario", "moe-dispatch-combine", "--imbalance",
                 "1,8", "-b", "4K", "-i", "1", "-r", "2",
                 "-l", str(log)]) == 0
    rows = []
    for p in sorted(log.glob("tpu-*.log")):
        rows += [ResultRow.from_csv(ln)
                 for ln in p.read_text().splitlines()]
    assert rows and all(r.op == "scenario" for r in rows)
    assert all(r.algo == "moe-dispatch-combine" for r in rows)
    assert {r.imbalance for r in rows} == {1, 8}
    capsys.readouterr()
    assert main(["report", str(log)]) == 0
    out = capsys.readouterr().out
    assert "### Scenario steps" in out
    assert "scenario[moe-dispatch-combine]" in out
    assert "all_to_all_v 50%" in out


def test_scenario_daemon_and_precompile_row_parity(eight_devices,
                                                  tmp_path):
    """A scenario point through --precompile lands the identical row
    geometry as the serial build (the one-build-per-spec contract with
    the scenario/imbalance spec coordinates)."""
    from tpu_perf.cli import main

    streams = []
    for extra in ((), ("--precompile", "2")):
        log = tmp_path / ("p" if extra else "s")
        assert main(["scenario", "moe-dispatch-combine,pipeline-chain",
                     "-b", "4K", "-i", "1", "-r", "2", *extra,
                     "-l", str(log)]) == 0
        rows = []
        for p in sorted(log.glob("tpu-*.log")):
            rows += [ResultRow.from_csv(ln)
                     for ln in p.read_text().splitlines()]
        streams.append([(r.op, r.algo, r.nbytes, r.run_id, r.imbalance)
                        for r in rows])
    assert streams[0] == streams[1]


# ------------------------------- hier mixed-inner grammar (satellite 1)


def test_hier_mixed_inner_resolution():
    from tpu_perf.arena.hierarchy import hier_inners, resolve_hier

    inners, phases = hier_inners("allreduce", "hier-ring/native/bruck")
    assert inners == ("ring", "native", "bruck") and len(phases) == 3
    # single-inner names replicate across the composition
    inners, _ = hier_inners("allreduce", "hier-ring")
    assert inners == ("ring",) * 3
    inners, _ = hier_inners("all_gather", "hier")
    assert inners == ("native",) * 2
    with pytest.raises(ValueError, match="one inner per phase"):
        hier_inners("all_gather", "hier-ring/ring/ring")
    with pytest.raises(ValueError, match="no reduce_scatter schedule"):
        hier_inners("allreduce", "hier-bruck/native/ring")
    with pytest.raises(ValueError, match="unknown inner"):
        hier_inners("allreduce", "hier-ring/nope/ring")
    with pytest.raises(ValueError, match="registered"):
        hier_inners("allreduce", "hier-nope")
    # per-slot pow2: rhd only constrains the axis its phase runs over
    assert resolve_hier("reduce_scatter", "hier-rhd/native",
                        ("dcn", "ici"), (3, 4)) \
        == "hier-rhd/native:dcn=3+ici=4"
    with pytest.raises(ValueError, match="power-of-two"):
        resolve_hier("reduce_scatter", "hier-native/rhd",
                     ("dcn", "ici"), (3, 4))


def test_hier_mixed_inner_parity_on_mesh(eight_devices):
    import jax

    from tpu_perf.ops import build_op

    mesh = _mesh((2, 4), ("dcn", "ici"))
    nat = build_op("allreduce", mesh, 260, 2)
    want = np.asarray(jax.block_until_ready(
        nat.step(nat.example_input)), dtype=np.float64)
    mixed = build_op("allreduce", mesh, 260, 2,
                     algo="hier-ring/native/bruck")
    assert mixed.algo == "hier-ring/native/bruck:dcn=2+ici=4"
    got = np.asarray(jax.block_until_ready(
        mixed.step(mixed.example_input)), dtype=np.float64)
    np.testing.assert_allclose(got, want, rtol=5e-6)
    # the keyed mixed label round-trips through the row/report grammar
    from tpu_perf.arena.hierarchy import hier_axis_pairs

    assert hier_axis_pairs(mixed.algo) == (("dcn", 2), ("ici", 4))
    label = decorate_op("allreduce", mixed.algo)
    assert parse_op_label(label)[1] == mixed.algo


def test_hier_all_not_expanded_with_mixed_spellings(eight_devices):
    # --algo all keeps its registered-name expansion: mixed spellings
    # are explicit-request only (the product space is the operator's)
    from tpu_perf.runner import algos_for_options

    opts = Options(op="allreduce", algo="all")
    algos = algos_for_options(opts, "allreduce", 8,
                              mesh_axes=(("dcn", 2), ("ici", 4)))
    assert not any("/" in a for a in algos)
    assert any(a.startswith("hier:") for a in algos)
