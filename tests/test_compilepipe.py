"""Pipelined sweep engine (ISSUE 4): compile-spec keying, the bounded
look-ahead pipeline, phase self-profiling, and — the load-bearing claims —
that a pipelined sweep emits the exact row set of a serial sweep and a
pipelined chaos soak reproduces the serial soak's ledger byte for byte
(the precompile worker never executes a kernel, so nothing observable
moves; only where the compile time is spent does)."""

import glob
import io
import json
import os
import threading
import time

import jax
import pytest

from tpu_perf.compilepipe import (
    CompilePipeline,
    CompileSpec,
    PhaseTimer,
    aot_compile,
    enable_compile_cache,
)
from tpu_perf.config import Options
from tpu_perf.driver import Driver, _ExternOp
from tpu_perf.parallel import make_mesh
from tpu_perf.schema import ResultRow


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh((), ())


def _row_keys(rows):
    return sorted((r.op, r.nbytes, r.iters, r.run_id) for r in rows)


def _log_row_keys(folder):
    (log,) = glob.glob(os.path.join(folder, "tpu-*.log"))
    with open(log) as fh:
        return _row_keys([ResultRow.from_csv(ln) for ln in fh.read().splitlines()])


# --- compile-spec keying -------------------------------------------------


def test_compile_spec_distinct_fields_never_collide():
    # every field of the build identity is load-bearing: flipping any ONE
    # of them must produce a distinct key (a collision would hand one
    # point another point's compiled program)
    base = dict(op="ring", nbytes=64, iters=2, dtype="float32",
                axis=None, window=1)
    variants = [
        {"op": "exchange"}, {"nbytes": 128}, {"iters": 4},
        {"dtype": "bfloat16"}, {"axis": ("x",)}, {"window": 2},
    ]
    specs = {CompileSpec(**base)}
    for v in variants:
        specs.add(CompileSpec(**{**base, **v}))
    assert len(specs) == 1 + len(variants)


def test_compile_spec_equal_specs_hit():
    # the str / 1-tuple spellings of the same single axis normalize to
    # one key (mirroring ops.collectives._flat_axes)
    a = CompileSpec.make("ring", 64, 2, dtype="float32", axis="x")
    b = CompileSpec.make("ring", 64, 2, dtype="float32", axis=("x",))
    assert a == b and hash(a) == hash(b)


def test_pipeline_builds_each_distinct_spec_once():
    built = []
    plan = ["a", "b", "a", "c", "a"]  # equal keys hit, never rebuild
    pipe = CompilePipeline(lambda k: built.append(k) or f"art-{k}",
                           plan, depth=2)
    try:
        got = [pipe.get(k) for k in plan]
    finally:
        pipe.close()
    assert got == ["art-a", "art-b", "art-a", "art-c", "art-a"]
    assert sorted(built) == ["a", "b", "c"]
    assert pipe.builds == 3


def test_pipeline_look_ahead_is_bounded():
    # with nothing consumed, the worker must stop after `depth` builds —
    # the HBM cap on resident example buffers
    built = []
    done = threading.Event()
    depth = 2

    def build(k):
        built.append(k)
        if len(built) >= depth:
            done.set()
        return k

    pipe = CompilePipeline(build, list(range(6)), depth=depth)
    try:
        assert done.wait(timeout=10)
        time.sleep(0.2)  # give an over-eager worker rope to hang itself
        assert len(built) == depth
        # consuming one credit releases exactly one more build
        assert pipe.get(0) == 0
        deadline = time.time() + 10
        while len(built) < depth + 1 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)
        assert len(built) == depth + 1
    finally:
        pipe.close()


def test_pipeline_build_error_surfaces_at_get():
    def build(k):
        if k == "bad":
            raise ValueError("invalid combination")
        return k

    pipe = CompilePipeline(build, ["ok", "bad"], depth=2)
    try:
        assert pipe.get("ok") == "ok"
        with pytest.raises(ValueError, match="invalid combination"):
            pipe.get("bad")
    finally:
        pipe.close()


def test_pipeline_get_unplanned_key_raises():
    pipe = CompilePipeline(lambda k: k, ["a"], depth=1)
    try:
        with pytest.raises(KeyError):
            pipe.get("never-planned")
    finally:
        pipe.close()


# --- phase timer ---------------------------------------------------------


def test_phase_timer_accumulates_and_snapshots():
    t = {"now": 0.0}
    timer = PhaseTimer(perf_clock=lambda: t["now"])
    timer.start()
    with timer.phase("compile"):
        t["now"] += 2.0
    with timer.phase("measure"):
        t["now"] += 1.0
    timer.add("compile", 0.5)  # the worker-thread contribution path
    timer.stop()
    snap = timer.snapshot()
    assert snap == {"compile_s": 2.5, "measure_s": 1.0, "log_s": 0.0}
    assert timer.wall_s == 3.0


# --- AOT compilation -----------------------------------------------------


def test_aot_compile_replaces_step_and_preserves_result(mesh):
    import numpy as np

    from tpu_perf.ops import build_op

    built = build_op("ring", mesh, 64, 2)
    ref = np.asarray(built.step(built.example_input))
    compiled = aot_compile(built)
    assert compiled.step is not built.step
    assert not hasattr(compiled.step, "lower")  # a Compiled executable
    np.testing.assert_allclose(np.asarray(compiled.step(built.example_input)),
                               ref)
    # idempotent: an already-compiled step passes through
    assert aot_compile(compiled).step is compiled.step


def test_aot_compile_passes_stand_ins_through():
    ext = _ExternOp("extern", 64, 10, 8)
    assert aot_compile(ext) is ext
    assert aot_compile(None) is None


# --- serial / pipelined equivalence --------------------------------------


def test_finite_sweep_pipelined_matches_serial(mesh):
    kw = dict(op="ring,hbm_stream", iters=2, num_runs=2, sweep="8,64")
    serial = Driver(Options(**kw), mesh, err=io.StringIO()).run()
    piped = Driver(Options(**kw, precompile=3), mesh, err=io.StringIO()).run()
    assert _row_keys(serial) == _row_keys(piped)
    assert len(serial) == 8  # 2 ops x 2 sizes x 2 runs


def test_finite_sweep_pipelined_matches_serial_slope(mesh):
    # the fence that doubles the compile count (a hi-iters twin per
    # point) — the pipeline must hand over both halves of the pair.
    # Sizes/iters are big enough that t_hi decisively exceeds t_lo:
    # a noise-dropped slope sample would make the two row sets differ
    # for reasons unrelated to the engine under test.
    kw = dict(op="ring", iters=4, num_runs=1, sweep="256K,1M",
              fence="slope")
    serial = Driver(Options(**kw), mesh, err=io.StringIO()).run()
    piped = Driver(Options(**kw, precompile=2), mesh, err=io.StringIO()).run()
    assert _row_keys(serial) == _row_keys(piped) and len(piped) == 2


def test_daemon_pipelined_matches_serial(mesh, tmp_path):
    kw = dict(op="ring,exchange", iters=1, num_runs=-1, sweep="8,32")
    Driver(Options(**kw, logfolder=str(tmp_path / "s")), mesh,
           err=io.StringIO(), max_runs=10).run()
    Driver(Options(**kw, precompile=4, logfolder=str(tmp_path / "p")), mesh,
           err=io.StringIO(), max_runs=10).run()
    assert _log_row_keys(str(tmp_path / "s")) == \
        _log_row_keys(str(tmp_path / "p"))


def test_run_sweep_pipelined_matches_serial(mesh):
    from tpu_perf.runner import run_sweep

    kw = dict(op="ring", iters=2, num_runs=2, sweep="8,64", fence="block")

    def keys(opts):
        return [(p.op, p.nbytes, p.iters, len(p.times.samples))
                for p in run_sweep(opts, mesh)]

    assert keys(Options(**kw)) == keys(Options(**kw, precompile=2))


def test_chaos_ledger_identical_under_precompile(mesh, tmp_path):
    """The determinism gate: same seed + spec => byte-identical
    chaos-*.log ledger whether the kernels were precompiled in the
    background or built inline (the injector sees the same (op, nbytes,
    run_id) stream because measurement order is untouched)."""
    from tpu_perf.faults import parse_fault_arg

    def soak(folder, precompile):
        opts = Options(
            op="ring,exchange", iters=1, num_runs=-1, sweep="8,32",
            synthetic_s=0.001, fault_seed=7, precompile=precompile,
            faults=[parse_fault_arg("spike:ring:32:5-10:30.0"),
                    parse_fault_arg("delay:ring:8:12-30:3.0")],
            logfolder=str(folder), health=True, stats_every=10,
            health_warmup=5,
        )
        Driver(opts, mesh, err=io.StringIO(), max_runs=40).run()
        files = sorted(glob.glob(str(folder / "chaos-*.log*")))
        assert files, "soak wrote no ledger"
        return b"".join(open(f, "rb").read() for f in files)

    assert soak(tmp_path / "serial", 0) == soak(tmp_path / "piped", 4)


# --- self-profiling observables ------------------------------------------


def test_heartbeat_json_carries_phase_totals(mesh):
    err = io.StringIO()
    opts = Options(op="ring", iters=1, num_runs=4, buff_sz=32,
                   stats_every=2, heartbeat_format="json", precompile=2)
    Driver(opts, mesh, err=err).run()
    beats = [json.loads(ln) for ln in err.getvalue().splitlines()
             if ln.startswith("{")]
    assert beats
    for b in beats:
        assert set(b["phase"]) == {"compile_s", "measure_s", "log_s"}
    last = beats[-1]["phase"]
    assert last["compile_s"] > 0 and last["measure_s"] > 0


def test_phase_sidecar_written_and_reported(mesh, tmp_path):
    from tpu_perf.report import phases_to_markdown, read_phases

    opts = Options(op="ring", iters=1, num_runs=2, sweep="8,32",
                   precompile=2, logfolder=str(tmp_path))
    Driver(opts, mesh, err=io.StringIO()).run()
    (entry,) = read_phases(str(tmp_path))
    assert entry["precompile"] == 2 and entry["rank"] == 0
    assert entry["wall_s"] > 0
    assert entry["phase"]["compile_s"] > 0
    table = phases_to_markdown([entry])
    assert "compile" in table and f"| {entry['rank']} " in table
    # a glob/file target never scans for sidecars
    assert read_phases(str(tmp_path / "tpu-*.log")) == []


def test_report_cli_renders_phase_breakdown(mesh, tmp_path, capsys):
    from tpu_perf.cli import main as cli_main

    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=32,
                   logfolder=str(tmp_path))
    Driver(opts, mesh, err=io.StringIO()).run()
    assert cli_main(["report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "### Harness phases" in out and "compile/wall" in out


def test_bench_payload_carries_phases(eight_devices, capsys, monkeypatch):
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner
    from tpu_perf.timing import RunTimes

    def fake_run_point(opts, mesh, nbytes, **kw):
        phases = kw.get("phases")
        if phases is not None:
            phases.add("compile", 0.25)
            phases.add("measure", 0.5)
        from tpu_perf.runner import SweepPointResult

        return SweepPointResult(
            op=opts.op, nbytes=nbytes, iters=opts.iters, n_devices=8,
            times=RunTimes(samples=[1e-5] * opts.num_runs, warmup_s=0.0,
                           overhead_s=0.0),
        )

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    assert data["phases"]["compile_s"] == 0.25
    assert data["phases"]["measure_s"] == 0.5
    assert data["phases"]["wall_s"] >= 0


# --- satellite fixes -----------------------------------------------------


def test_measure_overhead_identity_is_hoisted(mesh):
    """measure_overhead used to mint a fresh jax.jit(lambda y: y) per
    call — a new cache entry per sweep point under --measure-dispatch;
    the module-scope identity's cache must not grow on repeat calls."""
    import jax.numpy as jnp

    from tpu_perf import timing

    x = jnp.zeros(16)
    timing.measure_overhead(x, reps=1)
    n1 = timing._identity_step._cache_size()
    timing.measure_overhead(x, reps=1)
    timing.measure_overhead(x, reps=1)
    assert timing._identity_step._cache_size() == n1
    # a distinct spec adds exactly one entry, not one per call
    timing.measure_overhead(jnp.zeros(32), reps=1)
    timing.measure_overhead(jnp.zeros(32), reps=1)
    assert timing._identity_step._cache_size() == n1 + 1


def test_finite_sweep_dedupes_equal_spec_buffers(mesh):
    """Satellite: the daemon's canon example-buffer dedup now covers the
    finite sweep path — equal-spec points that are LIVE together share
    ONE device buffer, and a completed point's references retire so a
    serial wide sweep frees each point's buffers as it always did."""
    opts = Options(op="ring,hbm_stream", iters=1, num_runs=1, buff_sz=32)
    d = Driver(opts, mesh, err=io.StringIO())
    # two live pairs of the same (shape, dtype, sharding) spec: one buffer
    ring = d._build_cold("ring", "native", 32)
    hbm = d._build_cold("hbm_stream", "native", 32)
    assert hbm[0].example_input is ring[0].example_input
    assert len(d._canon) == 1
    # retirement is refcounted: the shared entry survives the first
    # retire and leaves with the last
    d._retire_pair(ring)
    assert len(d._canon) == 1
    d._retire_pair(hbm)
    assert d._canon == {} and d._canon_refs == {}


def test_finite_sweep_leaves_no_resident_buffers(mesh):
    """A finished finite sweep must not pin its example buffers for the
    driver's lifetime (the daemon does, by design — its plan stays
    resident): serial and pipelined runs both end with an empty canon."""
    for precompile in (0, 2):
        opts = Options(op="ring,hbm_stream", iters=1, num_runs=1,
                       sweep="8,32", precompile=precompile)
        d = Driver(opts, mesh, err=io.StringIO())
        d.run()
        assert d._canon == {} and d._canon_refs == {}, f"{precompile=}"


def test_daemon_keeps_canon_resident(mesh):
    # the daemon never retires: its kernels AND canonical buffers stay
    # resident for the round-robin's lifetime (one per distinct spec)
    opts = Options(op="ring,hbm_stream", iters=1, num_runs=-1, buff_sz=32)
    d = Driver(opts, mesh, err=io.StringIO(), max_runs=4)
    d.run()
    assert len(d._canon) == 1 and d._canon_refs != {}


# --- persistent compile cache --------------------------------------------


@pytest.fixture
def restored_compile_cache_config():
    old = jax.config.jax_compilation_cache_dir
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
        try:
            from jax.experimental.compilation_cache import compilation_cache

            compilation_cache.reset_cache()
        except Exception:  # noqa: BLE001 — best-effort detach
            pass


def test_enable_compile_cache_writes_entries(mesh, tmp_path,
                                             restored_compile_cache_config):
    cache = tmp_path / "cc"
    assert enable_compile_cache(str(cache)) == str(cache)
    assert cache.is_dir()
    assert jax.config.jax_compilation_cache_dir == str(cache)
    opts = Options(op="ring", iters=1, num_runs=1, buff_sz=32,
                   compile_cache=str(cache))
    Driver(opts, mesh, err=io.StringIO()).run()
    assert glob.glob(str(cache / "*-cache")), \
        "no persistent cache entries written"


# --- CLI surface ---------------------------------------------------------


def test_cli_flags_parse():
    from tpu_perf.cli import build_parser

    p = build_parser()
    for argv in (["run"], ["monitor"], ["chaos"]):
        args = p.parse_args(argv + ["--precompile", "4",
                                    "--compile-cache", "/tmp/x"])
        assert args.precompile == 4 and args.compile_cache == "/tmp/x"
    lm = p.parse_args(["linkmap", "--precompile", "2",
                       "--compile-cache", "/tmp/y"])
    assert lm.precompile == 2 and lm.compile_cache == "/tmp/y"


def test_options_reject_negative_precompile():
    with pytest.raises(ValueError, match="precompile"):
        Options(precompile=-1)


def test_linkmap_prober_pipelined_matches_serial(mesh):
    from tpu_perf.linkmap import LinkProber, plan_mesh_links

    schedules = plan_mesh_links((8,), ("x",))

    def keys(prober):
        result = prober.probe(schedules)
        assert all(p.bw_gbps and p.bw_gbps > 0 for p in result.probes)
        return sorted((p.probe.src, p.probe.dst) for p in result.probes)

    serial = keys(LinkProber(mesh, nbytes=1024, iters=1, runs=1))
    piped = keys(LinkProber(mesh, nbytes=1024, iters=1, runs=1,
                            precompile=3))
    assert serial == piped and len(serial) == 16
