from tpu_perf.report import aggregate, collect_paths, read_rows, to_csv, to_markdown
from tpu_perf.schema import RESULT_HEADER, ResultRow, timestamp_now


def _row(op="allreduce", nbytes=1024, lat=10.0, busbw=5.0, run_id=1):
    return ResultRow(
        timestamp=timestamp_now(), job_id="j", backend="jax", op=op,
        nbytes=nbytes, iters=10, run_id=run_id, n_devices=8,
        lat_us=lat, algbw_gbps=busbw / 1.75, busbw_gbps=busbw, time_ms=lat / 100,
    )


def _write(path, rows, header=False):
    with open(path, "w") as fh:
        if header:
            fh.write(RESULT_HEADER + "\n")
        for r in rows:
            fh.write(r.to_csv() + "\n")


def test_read_rows_skips_header(tmp_path):
    p = tmp_path / "tpu-a.log"
    _write(p, [_row(), _row(run_id=2)], header=True)
    rows = read_rows([str(p)])
    assert len(rows) == 2


def test_collect_paths_modes(tmp_path):
    a = tmp_path / "tpu-a.log"
    b = tmp_path / "tpu-b.log"
    other = tmp_path / "tcp-c.log"
    for p in (a, b, other):
        _write(p, [_row()])
    assert collect_paths(str(a)) == [str(a)]
    assert collect_paths(str(tmp_path)) == [str(a), str(b)]  # tpu-* only
    assert collect_paths(str(tmp_path / "tpu-*.log")) == [str(a), str(b)]
    assert collect_paths(str(tmp_path / "nope-*.log")) == []


def test_aggregate_groups_and_stats():
    rows = [
        _row(lat=10.0, busbw=5.0, run_id=1),
        _row(lat=20.0, busbw=4.0, run_id=2),
        _row(op="ring", nbytes=64, lat=1.0, busbw=9.0),
    ]
    points = aggregate(rows)
    assert len(points) == 2
    ar = next(p for p in points if p.op == "allreduce")
    assert ar.runs == 2
    assert ar.lat_us["min"] == 10.0 and ar.lat_us["max"] == 20.0
    assert ar.lat_us["p50"] == 15.0
    assert ar.busbw_gbps["max"] == 5.0


def test_markdown_and_csv_render():
    points = aggregate([_row(), _row(nbytes=1 << 30, op="ring")])
    md = to_markdown(points)
    assert "| jax | allreduce | 1K | float32 | 8 |" in md
    assert "| jax | ring | 1G |" in md
    csv = to_csv(points)
    assert csv.splitlines()[0].startswith("backend,op,nbytes")
    assert len(csv.splitlines()) == 3


def test_cli_report_end_to_end(tmp_path, capsys):
    from tpu_perf.cli import main

    p = tmp_path / "tpu-x.log"
    _write(p, [_row(run_id=i) for i in range(1, 6)])
    rc = main(["report", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "| jax | allreduce | 1K | float32 | 8 | oneshot | 5 |" in out
    rc = main(["report", str(tmp_path / "none-*.log")])
    assert rc == 1


def test_to_json_round_trips():
    import json

    from tpu_perf.report import to_json

    points = aggregate([_row(), _row(run_id=2, lat=20.0)])
    data = json.loads(to_json(points))
    assert len(data) == 1
    p = data[0]
    assert p["op"] == "allreduce" and p["runs"] == 2
    assert p["lat_us"]["p50"] == 15.0
    assert set(p["busbw_gbps"]) == {"min", "max", "avg", "p50", "p95", "p99"}


def test_cli_report_json(tmp_path, capsys):
    import json

    from tpu_perf.cli import main

    p = tmp_path / "tpu-a.log"
    _write(p, [_row(), _row(run_id=2)], header=True)
    assert main(["report", str(p), "--format", "json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data[0]["runs"] == 2


def test_backends_do_not_pool():
    import dataclasses

    points = aggregate([_row(), dataclasses.replace(_row(), backend="mpi")])
    assert len(points) == 2
    assert {p.backend for p in points} == {"jax", "mpi"}


def test_legacy_aggregation(tmp_path):
    from tpu_perf.report import aggregate_legacy, read_legacy_rows
    from tpu_perf.schema import LegacyRow

    p = tmp_path / "tcp-j-2-x.log"
    rows = [
        LegacyRow(timestamp="t", job_id="j", rank=r, vm_count=2,
                  local_ip="a", remote_ip="b", num_flows=10,
                  buffer_size=456131, num_buffers=10,
                  time_taken_ms=5.0 + r, run_id=1)
        for r in (2, 3)
    ]
    p.write_text("".join(r.to_csv() + "\n" for r in rows))
    points = aggregate_legacy(read_legacy_rows([str(p)]))
    assert len(points) == 1
    pt = points[0]
    assert pt.buffer_size == 456131 and pt.num_flows == 10
    assert pt.rows == 2 and pt.ranks == 2
    assert pt.time_ms["p50"] == 7.5


def test_cli_report_legacy(tmp_path, capsys):
    from tpu_perf.cli import main
    from tpu_perf.schema import LegacyRow

    (tmp_path / "tcp-a.log").write_text(
        LegacyRow(timestamp="t", job_id="j", rank=1, vm_count=2,
                  local_ip="a", remote_ip="b", num_flows=1,
                  buffer_size=4194304, num_buffers=5000,
                  time_taken_ms=123.456, run_id=1).to_csv() + "\n"
    )
    assert main(["report", str(tmp_path), "--legacy"]) == 0
    out = capsys.readouterr().out
    assert "4M" in out and "123.456" in out
    # exclusive with --compare / non-markdown formats
    assert main(["report", str(tmp_path), "--legacy", "--compare"]) == 2


def test_compare_pallas_pairs_raw_and_xla():
    from tpu_perf.report import compare_pallas

    rows = [
        _row(op="ring", nbytes=64, busbw=4.0),
        _row(op="pl_ring", nbytes=64, busbw=8.0),
        _row(op="allreduce", nbytes=64, busbw=5.0),  # no pallas counterpart
    ]
    cmp = compare_pallas(aggregate(rows))
    assert [c.op for c in cmp] == ["allreduce", "ring"]
    ring = next(c for c in cmp if c.op == "ring")
    assert ring.busbw_ratio == 2.0  # pl 8 / xla 4
    lone = next(c for c in cmp if c.op == "allreduce")
    assert lone.pallas is None and lone.busbw_ratio is None


def test_compare_pallas_counterpart_map_pairs_hbm_copy_with_hbm_stream():
    # VERDICT r2 weak #1: the motivating pair — pl_hbm_copy has no op
    # literally named "hbm_copy"; it must land next to hbm_stream
    from tpu_perf.report import compare_pallas

    rows = [
        _row(op="hbm_stream", nbytes=1 << 20, busbw=650.0),
        _row(op="pl_hbm_copy", nbytes=1 << 20, busbw=315.0),
    ]
    (c,) = compare_pallas(aggregate(rows))
    assert c.op == "hbm_stream" and c.pallas_op == "pl_hbm_copy"
    assert c.xla is not None and c.pallas is not None
    assert c.busbw_ratio == 315.0 / 650.0


def test_compare_pallas_two_kernels_share_one_counterpart():
    # pl_all_gather and pl_all_gather_bidir are two implementations of the
    # same collective: each gets its own row against the one all_gather
    # curve, and the xla point is not duplicated into a one-sided row
    from tpu_perf.report import compare_pallas

    rows = [
        _row(op="all_gather", nbytes=64, busbw=4.0),
        _row(op="pl_all_gather", nbytes=64, busbw=6.0),
        _row(op="pl_all_gather_bidir", nbytes=64, busbw=8.0),
    ]
    cmp = compare_pallas(aggregate(rows))
    assert [(c.op, c.pallas_op) for c in cmp] == [
        ("all_gather", "pl_all_gather"),
        ("all_gather", "pl_all_gather_bidir"),
    ]
    assert [c.busbw_ratio for c in cmp] == [1.5, 2.0]
    assert all(c.xla.busbw_gbps["p50"] == 4.0 for c in cmp)


def test_compare_pallas_every_known_kernel_has_a_real_counterpart():
    # the map must stay total over PALLAS_OPS, and every counterpart must
    # name a real XLA op builder (not a prefix-stripped ghost)
    from tpu_perf.ops import OP_BUILDERS
    from tpu_perf.ops.pallas_ring import PALLAS_OPS
    from tpu_perf.report import PALLAS_COUNTERPARTS

    assert set(PALLAS_COUNTERPARTS) == set(PALLAS_OPS)
    for pl_op, base in PALLAS_COUNTERPARTS.items():
        assert base in OP_BUILDERS, f"{pl_op} -> {base} is not a real op"


def test_compare_pallas_ignores_mpi_rows():
    import dataclasses

    from tpu_perf.report import compare_pallas

    rows = [
        _row(op="ring", nbytes=64, busbw=4.0),
        dataclasses.replace(_row(op="ring", nbytes=64, busbw=9.0),
                            backend="mpi"),
    ]
    (c,) = compare_pallas(aggregate(rows))
    assert c.xla.busbw_gbps["p50"] == 4.0


def test_cli_report_compare_pallas(tmp_path, capsys):
    from tpu_perf.cli import main

    p = tmp_path / "tpu-a.log"
    _write(p, [_row(op="ring", nbytes=64, busbw=4.0),
               _row(op="pl_ring", nbytes=64, busbw=8.0)])
    assert main(["report", str(p), "--compare-pallas"]) == 0
    out = capsys.readouterr().out
    assert "pallas/xla" in out and "| 4 | 8 | 2 |" in out
    assert main(["report", str(p), "--compare", "--compare-pallas"]) == 2


def test_compare_pivots_backends():
    import dataclasses

    from tpu_perf.report import compare

    rows = [
        _row(busbw=10.0, lat=4.0),
        dataclasses.replace(_row(busbw=5.0, lat=8.0), backend="mpi",
                            n_devices=2),
        _row(op="ring", nbytes=64, busbw=3.0),  # jax-only key
    ]
    cmp = compare(aggregate(rows))
    assert len(cmp) == 2
    both = next(c for c in cmp if c.op == "allreduce")
    assert both.busbw_ratio == 2.0  # jax 10 / mpi 5
    assert both.latency_ratio == 2.0  # mpi 8 / jax 4 (>1 = jax better)
    only = next(c for c in cmp if c.op == "ring")
    assert only.mpi is None and only.busbw_ratio is None


def test_compare_prefers_largest_device_count():
    import dataclasses

    from tpu_perf.report import compare

    rows = [
        _row(busbw=10.0),
        dataclasses.replace(_row(busbw=99.0), n_devices=2),  # smaller mesh
        dataclasses.replace(_row(busbw=5.0), backend="mpi", n_devices=2),
    ]
    (c,) = compare(aggregate(rows))
    assert c.jax.n_devices == 8 and c.jax.busbw_gbps["p50"] == 10.0


def test_cli_report_compare(tmp_path, capsys):
    import dataclasses

    from tpu_perf.cli import main

    p = tmp_path / "tpu-a.log"
    _write(p, [_row(busbw=10.0),
               dataclasses.replace(_row(busbw=5.0), backend="mpi")])
    assert main(["report", str(p), "--compare"]) == 0
    out = capsys.readouterr().out
    assert "jax/mpi bw" in out
    assert "| 10 | 5 | 2 |" in out
    # --compare is markdown-only; a conflicting --format is an error
    assert main(["report", str(p), "--compare", "--format", "json"]) == 2


def test_dtypes_do_not_pool_and_render_distinctly():
    # VERDICT r2 #5: dtype keys the curve — a bf16 row moves twice the
    # elements per byte of an f32 row at the same nbytes
    import dataclasses

    points = aggregate([
        _row(busbw=10.0),
        dataclasses.replace(_row(busbw=12.0), dtype="bfloat16"),
    ])
    assert len(points) == 2
    assert {p.dtype for p in points} == {"float32", "bfloat16"}
    md = to_markdown(points)
    assert "| bfloat16 |" in md and "| float32 |" in md
    assert "dtype" in to_csv(points).splitlines()[0]


def test_compare_keys_on_dtype():
    import dataclasses

    from tpu_perf.report import compare

    rows = [
        _row(busbw=10.0),
        dataclasses.replace(_row(busbw=12.0), dtype="bfloat16"),
        dataclasses.replace(_row(busbw=5.0), backend="mpi"),
    ]
    cmp = compare(aggregate(rows))
    assert len(cmp) == 2  # (allreduce, 1K, f32) paired; (.., bf16) one-sided
    paired = next(c for c in cmp if c.dtype == "float32")
    assert paired.busbw_ratio == 2.0
    lone = next(c for c in cmp if c.dtype == "bfloat16")
    assert lone.mpi is None


def test_result_row_dtype_column_back_compat():
    # rows logged before each trailing column existed still parse:
    # 12 fields = pre-dtype (-> float32), 13 = pre-mode (-> oneshot,
    # 0.0), 15 = pre-adaptive (-> fixed-budget marker 0,0,0.0)
    row = _row()
    line = row.to_csv()
    assert line.endswith(",float32,oneshot,0.000,0,0,0")
    line13 = ",".join(line.split(",")[:13])
    parsed = ResultRow.from_csv(line13)
    assert parsed.dtype == "float32"
    assert parsed.mode == "oneshot" and parsed.overhead_us == 0.0
    assert parsed.runs_requested == 0 and parsed.ci_rel == 0.0
    line12 = ",".join(line.split(",")[:12])
    assert ResultRow.from_csv(line12) == parsed
    line15 = ",".join(line.split(",")[:15])
    assert ResultRow.from_csv(line15) == parsed
    assert ResultRow.from_csv(line) == parsed
    # 14- or 16-field lines are no schema revision: fail loudly
    import pytest

    for n in (14, 16):
        with pytest.raises(ValueError, match="fields"):
            ResultRow.from_csv(",".join(line.split(",")[:n]))


def test_read_rows_skips_pre_dtype_header(tmp_path):
    # logs captured before the dtype column have a 12-field header line;
    # report must keep parsing them (header skip matches any revision)
    old_header = RESULT_HEADER.rsplit(",dtype", 1)[0]
    row12 = ",".join(_row().to_csv().split(",")[:12])
    p = tmp_path / "tpu-old.log"
    p.write_text(old_header + "\n" + row12 + "\n")
    (row,) = read_rows([str(p)])
    assert row.dtype == "float32" and row.nbytes == 1024


def test_points_from_artifact_json_and_raw(tmp_path):
    import json

    from tpu_perf.report import points_from_artifact, to_json

    rows = [_row(), _row(run_id=2, lat=20.0)]
    raw = tmp_path / "tpu-a.log"
    _write(raw, rows, header=True)
    art = tmp_path / "curves.json"
    art.write_text(to_json(aggregate(rows)))
    from_json = points_from_artifact(str(art))
    from_raw = points_from_artifact(str(tmp_path))
    # raw rows round-trip through to_csv's float formatting, so metrics
    # agree approximately; the curve keys must agree exactly
    assert len(from_json) == len(from_raw) == 1
    j, r = from_json[0], from_raw[0]
    assert (j.backend, j.op, j.nbytes, j.dtype, j.n_devices, j.runs) == \
           (r.backend, r.op, r.nbytes, r.dtype, r.n_devices, r.runs)
    import pytest

    assert j.lat_us["p50"] == pytest.approx(r.lat_us["p50"])
    assert j.busbw_gbps["p50"] == pytest.approx(r.busbw_gbps["p50"])
    assert j.lat_us["p50"] == 15.0


def test_diff_points_verdicts():
    from tpu_perf.report import diff_points

    base = aggregate([
        _row(op="hbm_stream", busbw=650.0),
        _row(op="ring", nbytes=64, busbw=100.0),
        _row(op="all_gather", nbytes=64, busbw=50.0),
        _row(op="barrier", busbw=0.0, lat=10.0),
    ])
    new = aggregate([
        _row(op="hbm_stream", busbw=500.0),         # -23%: regressed
        _row(op="ring", nbytes=64, busbw=104.0),    # +4%: ok
        _row(op="all_gather", nbytes=64, busbw=80.0),  # +60%: improved
        _row(op="barrier", busbw=0.0, lat=15.0),    # lat +50%: regressed
        _row(op="halo", nbytes=64, busbw=5.0),      # new-only
    ])
    diffs = {d.op: d for d in diff_points(base, new)}
    assert diffs["hbm_stream"].verdict == "regressed"
    assert diffs["hbm_stream"].metric == "busbw p50"
    assert diffs["ring"].verdict == "ok"
    assert diffs["all_gather"].verdict == "improved"
    # latency-only op is judged on lat p50, rising = regression
    assert diffs["barrier"].metric == "lat p50"
    assert diffs["barrier"].verdict == "regressed"
    assert diffs["halo"].verdict == "new-only"
    assert diffs["halo"].delta_pct is None
    # symmetric: a base-only key surfaces too
    back = {d.op: d for d in diff_points(new, base)}
    assert back["halo"].verdict == "base-only"


def test_modes_do_not_pool_and_do_not_pair():
    # VERDICT r3 #9: daemon rows (systematically hot) aggregate under
    # their own curve key and never pair against one-shot baselines in
    # --diff — a hot daemon folder can't manufacture phantom gains
    import dataclasses

    from tpu_perf.report import diff_points

    daemon_rows = [dataclasses.replace(_row(busbw=800.0), mode="daemon")]
    points = aggregate([_row(busbw=650.0)] + daemon_rows)
    assert len(points) == 2
    assert {p.mode for p in points} == {"oneshot", "daemon"}
    diffs = diff_points(aggregate([_row(busbw=650.0)]),
                        aggregate(daemon_rows))
    # one-sided rows, no "improved" verdict from the hot daemon point
    assert sorted(d.verdict for d in diffs) == ["base-only", "new-only"]


def test_compare_chaos_pairs_chaos_against_clean_soak():
    """Chaos rows in the curve tables (ROADMAP satellite): the fault-
    injected soak's mode="chaos" curves join against the clean soak of
    the same spec, clean-daemon preferred over one-shot (same hot-loop
    bias), with >1 ratios reading as 'chaos worse'."""
    import dataclasses

    from tpu_perf.report import compare_chaos, compare_chaos_to_markdown

    chaos = dataclasses.replace(_row(lat=40.0, busbw=200.0), mode="chaos")
    daemon = dataclasses.replace(_row(lat=10.0, busbw=800.0), mode="daemon")
    oneshot = _row(lat=12.0, busbw=650.0)
    lonely = dataclasses.replace(_row(op="ring", lat=5.0), mode="chaos")
    pts = aggregate([chaos, daemon, oneshot, lonely])
    cmp = {c.op: c for c in compare_chaos(pts)}
    assert set(cmp) == {"allreduce", "ring"}  # clean-only keys dropped
    c = cmp["allreduce"]
    assert c.clean.mode == "daemon"  # daemon preferred over oneshot
    assert c.latency_ratio == 4.0    # chaos/clean: >1 = slower
    assert c.busbw_ratio == 4.0      # clean/chaos: >1 = less bandwidth
    # a chaos key with no control soak keeps a one-sided row
    assert cmp["ring"].clean is None
    assert cmp["ring"].latency_ratio is None
    md = compare_chaos_to_markdown([cmp["allreduce"], cmp["ring"]])
    assert "| allreduce |" in md and "| daemon |" in md
    assert "| ring |" in md and "| — |" in md


def test_chaos_rows_do_not_pool_with_daemon_rows():
    import dataclasses

    chaos = dataclasses.replace(_row(busbw=200.0), mode="chaos")
    daemon = dataclasses.replace(_row(busbw=800.0), mode="daemon")
    points = aggregate([chaos, daemon])
    assert {p.mode for p in points} == {"chaos", "daemon"}


def test_clean_compare_pivots_exclude_chaos_rows():
    """compare()/compare_pallas() present clean performance: a chaos
    row (fault-perturbed, possibly on the bigger mesh) must never win a
    pivot slot and masquerade as the backend's or kernel's curve."""
    import dataclasses

    from tpu_perf.report import compare, compare_pallas

    mpi = dataclasses.replace(_row(busbw=100.0), backend="mpi")
    chaos = dataclasses.replace(_row(busbw=5.0, run_id=2), mode="chaos",
                                n_devices=16)
    (c,) = compare(aggregate([mpi, _row(busbw=650.0), chaos]))
    assert c.jax.mode == "oneshot" and c.jax.busbw_gbps["p50"] == 650.0
    # chaos-only on one side: the slot stays empty, not fault-poisoned
    (c,) = compare(aggregate([mpi, chaos]))
    assert c.jax is None
    pl_chaos = dataclasses.replace(_row(op="pl_ring", busbw=5.0),
                                   mode="chaos")
    xla = _row(op="ring", busbw=650.0)
    cmp = compare_pallas(aggregate([pl_chaos, xla]))
    assert [(c.op, c.pallas) for c in cmp] == [("ring", None)]


def test_compare_prefers_oneshot_over_daemon():
    import dataclasses

    from tpu_perf.report import compare, compare_to_markdown

    mpi = dataclasses.replace(_row(busbw=100.0), backend="mpi")
    hot = dataclasses.replace(_row(busbw=800.0), mode="daemon")
    pts = aggregate([mpi, hot, _row(busbw=650.0)])
    (c,) = compare(pts)
    assert c.jax.mode == "oneshot" and c.jax.busbw_gbps["p50"] == 650.0
    # when a side has ONLY daemon rows the pivot must fall back to them —
    # and the table must say so (the ~20% hot bias is visible, not hidden)
    (c,) = compare(aggregate([mpi, hot]))
    assert c.jax.mode == "daemon"
    assert "| daemon/oneshot |" in compare_to_markdown([c])
    # a pure one-shot pair renders quietly
    (c,) = compare(aggregate([mpi, _row(busbw=650.0)]))
    assert "| oneshot |" in compare_to_markdown([c])


def test_diff_points_zero_base_metric_is_incomparable():
    # ADVICE r3: a bandwidth op whose base artifact recorded 0 busbw is a
    # corrupt/partial artifact — it must never silently judge 'ok', and
    # it must stay judged on busbw (the op's bus factor), not flip to
    # latency-only because one side recorded a 0
    from tpu_perf.report import diff_points

    base = aggregate([_row(op="ring", busbw=0.0, lat=10.0)])
    new = aggregate([_row(op="ring", busbw=100.0, lat=10.0)])
    (d,) = diff_points(base, new)
    assert d.metric == "busbw p50"
    assert d.verdict == "incomparable"
    assert d.delta_pct is None
    # and symmetrically for a zero new metric
    (back,) = diff_points(new, base)
    assert back.verdict == "incomparable"
    # both sides zero = both artifacts broken, which is no better:
    # still incomparable, still a gate trip
    (both,) = diff_points(aggregate([_row(op="ring", busbw=0.0)]),
                          aggregate([_row(op="ring", busbw=0.0)]))
    assert both.verdict == "incomparable"


def test_diff_points_distinct_keys_do_not_pair():
    from tpu_perf.report import diff_points

    import dataclasses

    base = aggregate([_row(op="ring", busbw=100.0)])
    bf16 = [dataclasses.replace(r, dtype="bfloat16")
            for r in [_row(op="ring", busbw=10.0)]]
    diffs = diff_points(base, aggregate(bf16))
    # different dtype = different curve: two one-sided rows, no ratio
    assert sorted(d.verdict for d in diffs) == ["base-only", "new-only"]


def test_diff_points_rejects_bad_threshold():
    import pytest

    from tpu_perf.report import diff_points

    with pytest.raises(ValueError):
        diff_points([], [], threshold_pct=0)


def test_cli_report_diff(tmp_path, capsys):
    from tpu_perf.cli import main
    from tpu_perf.report import to_json

    base_rows = [_row(op="hbm_stream", busbw=650.0, run_id=i)
                 for i in range(1, 4)]
    art = tmp_path / "base.json"
    art.write_text(to_json(aggregate(base_rows)))

    ok_dir = tmp_path / "ok"
    ok_dir.mkdir()
    _write(ok_dir / "tpu-a.log",
           [_row(op="hbm_stream", busbw=640.0, run_id=i) for i in range(1, 4)])
    assert main(["report", str(ok_dir), "--diff", str(art)]) == 0
    out = capsys.readouterr().out
    assert "| ok |" in out and "busbw p50" in out

    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    _write(bad_dir / "tpu-a.log",
           [_row(op="hbm_stream", busbw=300.0, run_id=i) for i in range(1, 4)])
    assert main(["report", str(bad_dir), "--diff", str(art)]) == 3
    captured = capsys.readouterr()
    assert "| regressed |" in captured.out
    assert "regressed beyond 10%" in captured.err
    # a looser threshold accepts the same drop
    assert main(["report", str(bad_dir), "--diff", str(art),
                 "--diff-threshold", "60"]) == 0
    capsys.readouterr()
    # usage errors
    assert main(["report", str(ok_dir), "--diff", str(art),
                 "--compare"]) == 2
    assert main(["report", str(ok_dir), "--diff", str(art),
                 "--legacy"]) == 2


def test_cli_report_diff_missing_point_fails_gate(tmp_path, capsys):
    # an instrument that stopped producing rows must fail the gate (the
    # publish script continues past crashes), unless subset comparison is
    # explicitly requested
    from tpu_perf.cli import main
    from tpu_perf.report import to_json

    base_rows = [_row(op="hbm_stream", busbw=650.0),
                 _row(op="mxu_gemm", nbytes=4096, busbw=500.0)]
    art = tmp_path / "base.json"
    art.write_text(to_json(aggregate(base_rows)))
    sub = tmp_path / "sub"
    sub.mkdir()
    _write(sub / "tpu-a.log", [_row(op="hbm_stream", busbw=655.0)])
    assert main(["report", str(sub), "--diff", str(art)]) == 3
    captured = capsys.readouterr()
    assert "missing from the new run" in captured.err
    assert main(["report", str(sub), "--diff", str(art),
                 "--diff-ignore-missing"]) == 0


def test_points_from_artifact_rejects_non_report_json(tmp_path):
    import pytest

    from tpu_perf.report import points_from_artifact

    bad = tmp_path / "other.json"
    bad.write_text('{"not": "a report artifact"}')
    with pytest.raises(ValueError, match="not a report"):
        points_from_artifact(str(bad))
    bad.write_text('[{"op": "x", "unexpected_field": 1}]')
    with pytest.raises(ValueError, match="not a report"):
        points_from_artifact(str(bad))


def test_compute_ops_get_derived_tflops():
    # mxu_gemm rows render a TFLOP/s column derived from per-op latency
    # and the op's FLOP model (2*m^3); bandwidth ops render a dash
    m = 128
    row = _row(op="mxu_gemm", nbytes=m * m * 4, lat=10.0)
    (pt,) = aggregate([row])
    want = 2 * m**3 / (10.0e-6) / 1e12
    import pytest as _pytest

    assert pt.tflops["p50"] == _pytest.approx(want)
    md = to_markdown([pt])
    assert "TFLOP/s p50" in md and f"{want:.4g}" in md
    (ring_pt,) = aggregate([_row(op="ring")])
    assert ring_pt.tflops is None
    assert to_markdown([ring_pt]).splitlines()[2].endswith("| — |")
    # json carries the block only for compute ops; old artifacts without
    # it still load (CurvePoint default)
    import json as _json

    from tpu_perf.report import to_json

    data = _json.loads(to_json([pt, ring_pt]))
    assert "tflops" in data[0] and "tflops" not in data[1]
    # csv carries the column too (blank for non-compute ops); the algo
    # column appears only when arena points exist, so a pure-native
    # artifact stays byte-identical to pre-arena output
    csv = to_csv([pt, ring_pt])
    assert csv.splitlines()[0].endswith(",tflops_p50")
    assert csv.splitlines()[1].endswith(f",{want:.6g}")
    assert csv.splitlines()[2].endswith(",")
    import dataclasses as _dc2

    arena_csv = to_csv([pt, _dc2.replace(ring_pt, algo="ring")])
    assert arena_csv.splitlines()[0].endswith(",tflops_p50,algo")
    assert arena_csv.splitlines()[1].endswith(",native")
    assert arena_csv.splitlines()[2].endswith(",ring")
    # bandwidth rows of ANY supported dtype aggregate without numpy
    # dtype registration ('bfloat16' is not a stock numpy dtype — a
    # clean install has no ml_dtypes on the report path)
    import dataclasses as _dc

    (bf,) = aggregate([_dc.replace(_row(op="hbm_stream"), dtype="bfloat16")])
    assert bf.tflops is None
    # foreign dtypes degrade to no-tflops rather than crash
    (weird,) = aggregate([_dc.replace(_row(op="mxu_gemm"), dtype="float64")])
    assert weird.tflops is None
