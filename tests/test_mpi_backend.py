"""Build and drive the native C baseline backend through its shim launcher.

The reference had no tests at all (SURVEY.md §4); here the C driver runs
end-to-end in-process-parallel via the pthread MPI shim and its CSV output
is validated against the same LegacyRow schema the JAX backend emits —
keeping one schema across two very different backends (SURVEY.md §7 hard
part (c))."""

import os
import pathlib
import shutil
import subprocess

import pytest

from tpu_perf.schema import LegacyRow

BACKEND_DIR = pathlib.Path(__file__).resolve().parent.parent / "backends" / "mpi"


@pytest.fixture(scope="module")
def shim_binary(tmp_path_factory):
    if shutil.which("gcc") is None and shutil.which("cc") is None:
        pytest.skip("no C compiler")
    subprocess.run(["make", "shim"], cwd=BACKEND_DIR, check=True,
                   capture_output=True)
    return BACKEND_DIR / "mpi_perf_shim"


def _run(shim_binary, tmp_path, np, driver_args, env=None):
    hosts_file = tmp_path / "group1"
    hosts_file.write_text("shimhost1\n")
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(
        [str(shim_binary), "-np", str(np), "--", "-f", str(hosts_file), *driver_args],
        capture_output=True, text=True, timeout=120, env=full_env,
    )


def test_bidir_two_ranks(shim_binary, tmp_path):
    res = _run(shim_binary, tmp_path, 2, ["-i", "100", "-b", "65536", "-r", "3"])
    assert res.returncode == 0, res.stderr
    assert "kernel=bidir" in res.stderr


def test_csv_rows_match_legacy_schema(shim_binary, tmp_path):
    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run(
        shim_binary, tmp_path, 4,
        ["-i", "20", "-b", "456131", "-r", "3", "-p", "2", "-u", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    files = sorted(logs.glob("tcp-*.log"))
    # only group-1 ranks (2 and 3) write logs, like the reference
    assert len(files) == 2
    for f in files:
        lines = f.read_text().splitlines()
        assert len(lines) == 3  # runs, warm-up run 0 skipped
        for i, line in enumerate(lines, start=1):
            row = LegacyRow.from_csv(line)  # parses in the reference schema
            assert row.buffer_size == 456131
            assert row.num_buffers == 20
            assert row.num_flows == 2
            assert row.run_id == i
            assert row.local_ip == "shimhost1"
            assert row.remote_ip == "shimhost0"


def test_pairwise_dual_schema_rows(shim_binary, tmp_path):
    # pairwise mode mirrors the jax driver's dual-schema logging: legacy
    # tcp-* rows plus extended tpu-* rows with jax-named ops, so both
    # backends' rows land on the same report curve keys
    from tpu_perf.schema import ResultRow

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run(
        shim_binary, tmp_path, 2,
        ["-i", "40", "-b", "65536", "-r", "3", "-x", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    assert len(list(logs.glob("tcp-*.log"))) == 1  # group-1 rank only
    ext = sorted(logs.glob("tpu-*.log"))
    assert len(ext) == 1
    rows = [ResultRow.from_csv(l) for l in ext[0].read_text().splitlines()]
    assert len(rows) == 3  # warm-up run 0 skipped
    for row in rows:
        assert row.backend == "mpi"
        assert row.op == "exchange"  # windowed non-blocking = jax exchange
        assert row.nbytes == 65536  # per-message, like the legacy BufferSize
        assert row.iters == 40
        assert row.n_devices == 2
        assert row.lat_us > 0
        assert row.busbw_gbps == pytest.approx(row.algbw_gbps)  # factor 1.0


def test_pairwise_pingpong_row_uses_one_way_time(shim_binary, tmp_path):
    # blocking bidirectional rows follow the jax round-trip convention:
    # lat_us is the one-way time (RTT/2), bandwidth per direction
    from tpu_perf.schema import ResultRow

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run(
        shim_binary, tmp_path, 2,
        ["-i", "50", "-b", "4096", "-r", "2", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    rows = [ResultRow.from_csv(l) for f in logs.glob("tpu-*.log")
            for l in f.read_text().splitlines()]
    assert rows and all(r.op == "pingpong" for r in rows)
    for r in rows:
        # time_ms covers 50 round trips; lat_us must be the halved per-iter
        assert r.lat_us == pytest.approx(r.time_ms * 1e3 / 50 / 2, rel=1e-2)


def test_windowed_rows_comparable_across_backends(shim_binary, tmp_path, eight_devices):
    # VERDICT r1 #2: one log folder holding the MPI baseline's windowed rows
    # and the jax windowed-exchange rows must aggregate to curve points with
    # the same (op, nbytes) key — per-message size, window folded into iters
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    from tpu_perf.parallel import make_mesh
    from tpu_perf.report import aggregate, collect_paths, read_rows

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run(
        shim_binary, tmp_path, 2,
        ["-i", "40", "-b", "65536", "-r", "3", "-x", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr

    opts = Options(
        op="exchange", window=4, nonblocking=True, buff_sz=65536, iters=10,
        num_runs=3, logfolder=str(logs),
    )
    Driver(opts, make_mesh()).run()

    points = aggregate(read_rows(collect_paths(str(logs))))
    exchange = [p for p in points if p.op == "exchange"]
    assert sorted(p.backend for p in exchange) == ["jax", "mpi"]
    assert all(p.nbytes == 65536 for p in exchange)  # same curve key
    assert all(p.runs == 3 for p in exchange)


def test_windowed_kernel_past_boundary(shim_binary, tmp_path):
    # 600 iters > the 256-slot window: exercises the boundary waitall + drain
    res = _run(shim_binary, tmp_path, 2, ["-i", "600", "-b", "4096", "-r", "2", "-x"])
    assert res.returncode == 0, res.stderr
    assert "kernel=windowed" in res.stderr


def test_gbps_report(shim_binary, tmp_path):
    res = _run(
        shim_binary, tmp_path, 2, ["-i", "50", "-b", "1048576", "-r", "2", "-x", "-B"],
        env={"TPU_PERF_STATS_EVERY": "1"},
    )
    assert res.returncode == 0, res.stderr
    assert "Gbps" in res.stderr


def test_rotation_fires_ingest_cmd(shim_binary, tmp_path):
    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run(
        shim_binary, tmp_path, 2,
        ["-i", "2000", "-b", "65536", "-r", "150", "-l", str(logs)],
        env={
            "TPU_PERF_LOG_ROTATE_SEC": "1",
            "TPU_PERF_INGEST_CMD": "echo INGEST-FIRED 1>&2",
        },
    )
    assert res.returncode == 0, res.stderr
    assert "INGEST-FIRED" in res.stderr
    assert len(list(logs.glob("tcp-*.log"))) >= 2  # rotated at least once


def test_reference_command_line_verbatim(shim_binary, tmp_path):
    # the reference's run scripts spell the flags
    #   -f GROUP1FILE -n NUM_GROUP1 -p FLOWS -u 1 -r RUNS -i ITERS -b BUFF -l LOG
    # (run-hbv3.sh:28, mpi_perf.c:273-339) — that exact line must drive
    # this backend unchanged (the operator boundary of the north star)
    hosts_file = tmp_path / "group1"
    hosts_file.write_text("shimhost1\n")
    logs = tmp_path / "logs"
    logs.mkdir()
    res = subprocess.run(
        [str(shim_binary), "-np", "4", "--",
         "-f", str(hosts_file), "-n", "1", "-p", "2", "-u", "1",
         "-r", "2", "-i", "10", "-b", "456131", "-l", str(logs)],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "kernel=oneway" in res.stderr
    rows = [LegacyRow.from_csv(l) for f in logs.glob("tcp-*.log")
            for l in f.read_text().splitlines()]
    assert rows and all(r.buffer_size == 456131 and r.num_flows == 2
                        for r in rows)


def test_group1_count_mismatch_aborts(shim_binary, tmp_path):
    # a -n that disagrees with the file is a config error, not a guess
    hosts_file = tmp_path / "group1"
    hosts_file.write_text("shimhost1\n")
    res = subprocess.run(
        [str(shim_binary), "-np", "2", "--", "-f", str(hosts_file),
         "-n", "3", "-i", "2", "-r", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode != 0
    assert "lists 1 hosts" in res.stderr


def test_large_group_file_no_cap(shim_binary, tmp_path):
    # the group list is heap-read with no size cap (the old build capped it
    # at 16 KiB): 4000 decoy hosts =~ 60 KiB, real host buried at the end
    hosts_file = tmp_path / "group1"
    decoys = "".join(f"fleet-node-{i:05d}.example\n" for i in range(4000))
    hosts_file.write_text(decoys + "shimhost1\n")
    res = subprocess.run(
        [str(shim_binary), "-np", "2", "--", "-f", str(hosts_file),
         "-i", "5", "-b", "4096", "-r", "1", "-u"],
        capture_output=True, text=True, timeout=120,
    )
    # unidirectional mode skips the exact-half validation, so the 4001-line
    # list is accepted and the run completes
    assert res.returncode == 0, res.stderr
    assert "kernel=oneway" in res.stderr


def test_shim_world_of_64_threads(shim_binary, tmp_path):
    # the driver no longer caps the world; the shim's own ceiling is 64
    # threads — the largest world must actually run (32 pairs)
    res = subprocess.run(
        [str(shim_binary), "-np", "64", "-hosts", "2", "--",
         "-f", str(_hosts32(tmp_path)), "-i", "3", "-b", "1024", "-r", "1",
         "-p", "32"],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr


def _hosts32(tmp_path):
    hosts_file = tmp_path / "group1-64"
    hosts_file.write_text("shimhost1\n")
    return hosts_file


def test_shim_beyond_64_threads_clear_error(shim_binary, tmp_path):
    # ranks beyond the pthread shim's ceiling fail loudly, not mysteriously
    res = subprocess.run(
        [str(shim_binary), "-np", "80", "--", "-f", str(_hosts32(tmp_path)),
         "-i", "1", "-r", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode != 0
    assert "out of range" in res.stderr


def test_group_mismatch_aborts(shim_binary, tmp_path):
    bad = tmp_path / "bad_hosts"
    bad.write_text("shimhost0\nshimhost1\n")
    res = subprocess.run(
        [str(shim_binary), "-np", "2", "--", "-f", str(bad), "-i", "1", "-r", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode != 0
    assert "group mismatch" in res.stderr


def test_missing_group_file_fails(shim_binary, tmp_path):
    res = subprocess.run(
        [str(shim_binary), "-np", "2", "--", "-i", "1", "-r", "1"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode != 0
    assert "-f" in res.stderr


def _run_coll(shim_binary, np, driver_args, env=None):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(
        [str(shim_binary), "-np", str(np), "--", *driver_args],
        capture_output=True, text=True, timeout=120, env=full_env,
    )


def test_collective_mode_rows_match_extended_schema(shim_binary, tmp_path):
    from tpu_perf.schema import ResultRow

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run_coll(
        shim_binary, 8,
        ["-o", "allreduce", "-b", "65536", "-i", "5", "-r", "3", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    assert "kernel=allreduce" in res.stderr
    files = sorted(logs.glob("tpu-*.log"))
    assert len(files) == 1  # rank 0 only writes extended rows
    lines = files[0].read_text().splitlines()
    assert len(lines) == 3  # warm-up run 0 skipped
    for i, line in enumerate(lines, start=1):
        row = ResultRow.from_csv(line)
        assert row.backend == "mpi"
        assert row.op == "allreduce"
        assert row.nbytes == 65536
        assert row.n_devices == 8
        assert row.run_id == i
        assert row.lat_us > 0 and row.busbw_gbps > 0
        # busbw = algbw * 2(n-1)/n for allreduce
        assert row.busbw_gbps == pytest.approx(row.algbw_gbps * 2 * 7 / 8, rel=1e-3)


@pytest.mark.parametrize("op", [
    "all_gather", "reduce_scatter", "all_to_all", "broadcast", "barrier",
])
def test_collective_ops_run(shim_binary, op):
    res = _run_coll(shim_binary, 4, ["-o", op, "-b", "4096", "-i", "3", "-r", "2"])
    assert res.returncode == 0, res.stderr
    assert f"kernel={op}" in res.stderr


def test_collective_barrier_latency_only_rows(shim_binary, tmp_path):
    from tpu_perf.schema import ResultRow

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run_coll(
        shim_binary, 4,
        ["-o", "barrier", "-b", "65536", "-i", "10", "-r", "2", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    rows = [ResultRow.from_csv(l) for f in logs.glob("tpu-*.log")
            for l in f.read_text().splitlines()]
    # nbytes=4: one float32 element, matching the jax barrier op
    assert rows and all(r.nbytes == 4 and r.busbw_gbps == 0.0 for r in rows)


def test_collective_report_interop(shim_binary, tmp_path):
    # the C backend's rows feed the same `tpu-perf report` as the jax rows
    from tpu_perf.report import aggregate, collect_paths, read_rows

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run_coll(
        shim_binary, 4,
        ["-o", "all_gather", "-b", "8192", "-i", "5", "-r", "4", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    points = aggregate(read_rows(collect_paths(str(logs))))
    assert len(points) == 1
    assert points[0].op == "all_gather" and points[0].runs == 4


def test_unknown_collective_rejected(shim_binary):
    res = _run_coll(shim_binary, 2, ["-o", "alreduce", "-i", "1", "-r", "1"])
    assert res.returncode != 0
    assert "unknown collective" in res.stderr


@pytest.mark.parametrize("op", [
    "allreduce", "all_gather", "reduce_scatter", "all_to_all", "broadcast",
])
def test_collective_nbytes_align_with_jax_backend(shim_binary, tmp_path, op):
    # at the awkward legacy size (456131, mpi_perf.c:14) both backends must
    # log the identical rounded nbytes, or their report curve points diverge
    from tpu_perf.ops import payload_elems
    from tpu_perf.schema import ResultRow

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run_coll(
        shim_binary, 8,
        ["-o", op, "-b", "456131", "-i", "2", "-r", "1", "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    rows = [ResultRow.from_csv(l) for f in logs.glob("tpu-*.log")
            for l in f.read_text().splitlines()]
    _, want = payload_elems(op, 456131, 8, 4)  # jax float32 rounding
    assert rows and all(r.nbytes == want for r in rows)


def test_collective_size_over_1gib_rejected(shim_binary):
    res = _run_coll(shim_binary, 2, ["-o", "broadcast", "-b", "2147483648",
                                     "-i", "1", "-r", "1"])
    assert res.returncode != 0
    assert "1 GiB" in res.stderr


def test_stream_local_rows_factor_two(shim_binary, tmp_path):
    # -o hbm_stream: per-rank local memory stream, busbw counts read+write
    from tpu_perf.schema import ResultRow

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run_coll(
        shim_binary, 2,
        ["-o", "hbm_stream", "-b", "1048576", "-i", "10", "-r", "3",
         "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    assert "kernel=hbm_stream" in res.stderr
    rows = [ResultRow.from_csv(l) for f in logs.glob("tpu-*.log")
            for l in f.read_text().splitlines()]
    assert len(rows) == 3
    for row in rows:
        assert row.op == "hbm_stream" and row.backend == "mpi"
        assert row.nbytes == 1048576 and row.dtype == "float32"
        assert row.busbw_gbps == pytest.approx(2 * row.algbw_gbps, rel=1e-3)
        assert row.busbw_gbps > 0


def test_stream_pairs_with_jax_rows_in_compare(shim_binary, tmp_path,
                                               eight_devices):
    # the whole point: host-DRAM rows and TPU-HBM rows land on ONE curve
    # key and report --compare prints a jax/mpi ratio for the memory
    # instrument, like it does for the collectives
    from tpu_perf.config import Options
    from tpu_perf.parallel import make_mesh
    from tpu_perf.report import aggregate, collect_paths, compare, read_rows
    from tpu_perf.runner import run_point

    logs = tmp_path / "logs"
    logs.mkdir()
    res = _run_coll(
        shim_binary, 2,
        ["-o", "hbm_stream", "-b", "262144", "-i", "5", "-r", "2",
         "-l", str(logs)],
    )
    assert res.returncode == 0, res.stderr
    mesh = make_mesh()
    opts = Options(op="hbm_stream", iters=2, num_runs=2)
    point = run_point(opts, mesh, 262144)
    with open(logs / "tpu-jax.log", "w") as fh:
        for row in point.rows("jobj"):
            fh.write(row.to_csv() + "\n")
    cmp = compare(aggregate(read_rows(collect_paths(str(logs)))))
    (c,) = [c for c in cmp if c.op == "hbm_stream"]
    assert c.jax is not None and c.mpi is not None
    assert c.busbw_ratio is not None and c.busbw_ratio > 0
