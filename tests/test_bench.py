"""The headline benchmark's JSON contract (the driver parses this line)."""

import json

import pytest

from tpu_perf.timing import RunTimes


def _fake_point(op, n_devices, samples):
    from tpu_perf.runner import SweepPointResult

    return SweepPointResult(
        op=op, nbytes=4 * 1024 * 1024, iters=16, n_devices=n_devices,
        times=RunTimes(samples=samples, warmup_s=0.0, overhead_s=0.0),
    )


@pytest.mark.parametrize("n_devices,metric_op", [(8, "allreduce"), (1, "hbm_stream")])
def test_bench_json_line(eight_devices, capsys, monkeypatch, n_devices, metric_op):
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:n_devices])
    captured = {}

    def fake_run_point(opts, mesh, nbytes, **kw):
        captured["op"] = opts.op
        captured["fence"] = opts.fence
        # fast enough that the 4 MiB fake payload clears the single-chip
        # plateau floor (the degraded-window marker has its own test)
        return _fake_point(opts.op, n_devices, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    # bench imports run_point inside main(); patching the runner module
    # covers both import styles
    bench.main()
    line = capsys.readouterr().out.strip()
    data = json.loads(line)  # ONE parseable JSON line
    assert captured["op"] == metric_op
    assert captured["fence"] == "slope"
    assert set(data) >= {"metric", "value", "unit", "vs_baseline"}
    assert data["unit"] == "GB/s"
    assert data["value"] > 0 and data["vs_baseline"] > 0
    assert data["runs_dropped"] == 0
    assert metric_op in data["metric"]
    # healthy passes carry no degraded marker
    assert "below_plateau_floor" not in data


def test_bench_marks_exhausted_retry_budget(eight_devices, capsys, monkeypatch):
    # ADVICE r2: when all 3 single-chip passes stay below the plateau floor
    # the JSON must say so — a consumer scripting on `value` cannot be left
    # to re-derive the floor from BASELINE.md
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    passes = {"n": 0}

    def degraded_run_point(opts, mesh, nbytes, **kw):
        passes["n"] += 1
        # 0.1 s per run at these sizes is ~60-100 GB/s: a degraded window
        return _fake_point(opts.op, 1, [0.1] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", degraded_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", degraded_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    assert passes["n"] == 6  # 2 operating points x 3 passes: budget exhausted
    assert data["below_plateau_floor"] is True
    assert 0 < data["value"] < bench.PLATEAU_FLOOR_GBPS
