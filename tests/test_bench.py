"""The headline benchmark's JSON contract (the driver parses this line)."""

import json

import pytest

from tpu_perf.timing import RunTimes


def _fake_point(op, n_devices, samples):
    from tpu_perf.runner import SweepPointResult

    return SweepPointResult(
        op=op, nbytes=4 * 1024 * 1024, iters=16, n_devices=n_devices,
        times=RunTimes(samples=samples, warmup_s=0.0, overhead_s=0.0),
    )


@pytest.mark.parametrize("n_devices,metric_op", [(8, "allreduce"), (1, "hbm_stream")])
def test_bench_json_line(eight_devices, capsys, monkeypatch, n_devices, metric_op):
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:n_devices])
    monkeypatch.setattr(bench, "_FENCE_PREFERENCE", ["trace", "slope"])
    captured = {"ops": [], "fences": []}

    def fake_run_point(opts, mesh, nbytes, **kw):
        captured["ops"].append(opts.op)
        captured["fences"].append(opts.fence)
        # fast enough that the 4 MiB fake payload clears the single-chip
        # plateau floor (the degraded-window marker has its own test)
        return _fake_point(opts.op, n_devices, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    # bench imports run_point inside main(); patching the runner module
    # covers both import styles
    bench.main()
    line = capsys.readouterr().out.strip()
    data = json.loads(line)  # ONE parseable JSON line
    assert captured["ops"][0] == metric_op
    # the device-clock trace fence is tried first on every instrument
    assert captured["fences"][0] == "trace"
    assert set(data) >= {"metric", "value", "unit", "vs_baseline", "metrics"}
    assert data["unit"] == "GB/s"
    assert data["value"] > 0 and data["vs_baseline"] > 0
    assert data["runs_dropped"] == 0
    assert metric_op in data["metric"]
    # healthy passes carry no degraded marker
    assert "below_plateau_floor" not in data
    if n_devices == 1:
        # VERDICT r3 #2: the round artifact carries BOTH single-chip
        # rooflines — memory (hbm_stream) and compute (mxu_gemm)
        assert "mxu_gemm" in captured["ops"]
        assert [m["metric"].split("_p50")[0] for m in data["metrics"]] == \
            ["hbm_stream_busbw", "mxu_gemm_tflops"]
        mxu = data["metrics"][1]
        assert mxu["unit"] == "TFLOP/s"
        assert mxu["value"] > 0 and mxu["fence"] == "trace"
    else:
        assert len(data["metrics"]) == 1


def test_bench_trace_fence_falls_back_to_slope(eight_devices, capsys, monkeypatch):
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner

    import jax

    from tpu_perf.traceparse import TraceUnavailableError

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    monkeypatch.setattr(bench, "_FENCE_PREFERENCE", ["trace", "slope"])
    trace_attempts = {"n": 0}

    def fake_run_point(opts, mesh, nbytes, **kw):
        if opts.fence == "trace":
            # what a CPU runtime's capture does: host lanes only
            trace_attempts["n"] += 1
            raise TraceUnavailableError("no /device:* lanes")
        return _fake_point(opts.op, 1, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    assert all(m["fence"] == "slope" for m in data["metrics"])
    # a runtime without device lanes never grows them: the doomed trace
    # attempt runs once, not once per measurement point
    assert trace_attempts["n"] == 1


def test_bench_marks_exhausted_retry_budget(eight_devices, capsys, monkeypatch):
    # ADVICE r2: when all 3 single-chip passes stay below the plateau floor
    # the JSON must say so — a consumer scripting on `value` cannot be left
    # to re-derive the floor from BASELINE.md
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    monkeypatch.setattr(bench, "_FENCE_PREFERENCE", ["trace", "slope"])
    passes = {"n": 0}

    def degraded_run_point(opts, mesh, nbytes, **kw):
        passes["n"] += 1
        # 0.1 s per run at these sizes is ~60-100 GB/s: a degraded window
        return _fake_point(opts.op, 1, [0.1] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", degraded_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", degraded_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    # stream: 2 operating points x 3 passes; mxu: 1 point x 3 passes
    assert passes["n"] == 9
    assert data["below_plateau_floor"] is True
    assert 0 < data["value"] < bench.PLATEAU_FLOOR_GBPS
    # the degraded marker is per instrument
    assert all(m["below_plateau_floor"] for m in data["metrics"])
