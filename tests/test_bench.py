"""The headline benchmark's JSON contract (the driver parses this line)."""

import json

import pytest

from tpu_perf.timing import RunTimes


def _fake_point(op, n_devices, samples):
    from tpu_perf.runner import SweepPointResult

    return SweepPointResult(
        op=op, nbytes=4 * 1024 * 1024, iters=16, n_devices=n_devices,
        times=RunTimes(samples=samples, warmup_s=0.0, overhead_s=0.0),
    )


@pytest.mark.parametrize("n_devices,metric_op", [(8, "allreduce"), (1, "hbm_stream")])
def test_bench_json_line(eight_devices, capsys, monkeypatch, n_devices, metric_op):
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner
    import tpu_perf.timing as timing

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:n_devices])
    # pretend the runtime records device lanes so trace is preferred
    monkeypatch.setattr(timing, "trace_fence_available", lambda: True)
    captured = {"ops": [], "fences": []}

    def fake_run_point(opts, mesh, nbytes, **kw):
        captured["ops"].append(opts.op)
        captured["fences"].append(opts.fence)
        # fast enough that the 4 MiB fake payload clears the single-chip
        # plateau floor (the degraded-window marker has its own test)
        return _fake_point(opts.op, n_devices, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    # bench imports run_point inside main(); patching the runner module
    # covers both import styles
    bench.main()
    line = capsys.readouterr().out.strip()
    data = json.loads(line)  # ONE parseable JSON line
    assert captured["ops"][0] == metric_op
    # the device-clock trace fence is tried first on every instrument
    assert captured["fences"][0] == "trace"
    assert set(data) >= {"metric", "value", "unit", "vs_baseline", "metrics"}
    assert data["unit"] == "GB/s"
    assert data["value"] > 0 and data["vs_baseline"] > 0
    assert data["runs_dropped"] == 0
    assert metric_op in data["metric"]
    # healthy passes carry no degraded marker
    assert "below_plateau_floor" not in data
    if n_devices == 1:
        # VERDICT r3 #2 + round 5: the round artifact carries the
        # single-chip rooflines — memory (hbm_stream), the 2R:1W mixed
        # point (hbm_triad), and compute (mxu_gemm)
        assert "mxu_gemm" in captured["ops"]
        assert [m["metric"].split("_p50")[0] for m in data["metrics"]] == \
            ["hbm_stream_busbw", "hbm_triad_busbw", "mxu_gemm_tflops"]
        mxu = data["metrics"][2]
        assert mxu["unit"] == "TFLOP/s"
        assert mxu["value"] > 0 and mxu["fence"] == "trace"
    else:
        assert len(data["metrics"]) == 1


def test_bench_probe_skips_trace_entirely(eight_devices, capsys, monkeypatch):
    # the probe (not a doomed first measurement) decides the fence list:
    # a runtime without device lanes never attempts a trace capture at all
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner
    import tpu_perf.timing as timing

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    monkeypatch.setattr(timing, "trace_fence_available", lambda: False)
    fences_seen = []

    def fake_run_point(opts, mesh, nbytes, **kw):
        fences_seen.append(opts.fence)
        return _fake_point(opts.op, 1, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    assert all(m["fence"] == "slope" for m in data["metrics"])
    assert "trace" not in fences_seen


def test_bench_trace_fence_falls_back_to_slope(eight_devices, capsys, monkeypatch):
    # safety net: probe said trace, but captures raise anyway — each
    # measurement falls back to slope instead of dying
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner
    import tpu_perf.timing as timing

    import jax

    from tpu_perf.traceparse import TraceUnavailableError

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    monkeypatch.setattr(timing, "trace_fence_available", lambda: True)
    # the fallback path latches timing._TRACE_PROBED = False; register the
    # attribute with monkeypatch so the latch cannot leak across tests
    monkeypatch.setattr(timing, "_TRACE_PROBED", None)

    def fake_run_point(opts, mesh, nbytes, **kw):
        if opts.fence == "trace":
            raise TraceUnavailableError("no /device:* lanes")
        return _fake_point(opts.op, 1, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    assert all(m["fence"] == "slope" for m in data["metrics"])


def test_bench_marks_exhausted_retry_budget(eight_devices, capsys, monkeypatch):
    # ADVICE r2: when all 3 single-chip passes stay below the plateau floor
    # the JSON must say so — a consumer scripting on `value` cannot be left
    # to re-derive the floor from BASELINE.md
    import tpu_perf.bench as bench
    import tpu_perf.runner as runner
    import tpu_perf.timing as timing

    import jax

    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    monkeypatch.setattr(timing, "trace_fence_available", lambda: True)
    passes = {"n": 0}

    def degraded_run_point(opts, mesh, nbytes, **kw):
        passes["n"] += 1
        # 0.1 s per run at these sizes is ~60-100 GB/s: a degraded window
        return _fake_point(opts.op, 1, [0.1] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", degraded_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", degraded_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    # stream + triad: 2 operating points x 3 passes each; mxu: 1 x 3
    assert passes["n"] == 15
    assert data["below_plateau_floor"] is True
    from tpu_perf.chips import V5E  # the CPU runtime falls back to v5e

    assert 0 < data["value"] < V5E.stream_floor_gbps
    # the degraded marker is per instrument
    assert all(m["below_plateau_floor"] for m in data["metrics"])


def test_bench_specs_follow_detected_chip(eight_devices, capsys, monkeypatch):
    # VERDICT r4 #1: bench's nominals/floors come from the chip table,
    # not hardwired v5e constants — on a v5p the denominators change
    import tpu_perf.bench as bench
    import tpu_perf.chips as chips
    import tpu_perf.runner as runner
    import tpu_perf.timing as timing

    import jax

    v5p = chips.CHIPS["v5p"]
    monkeypatch.setattr(jax, "devices", lambda: jax.local_devices()[:1])
    monkeypatch.setattr(timing, "trace_fence_available", lambda: False)
    monkeypatch.setattr(chips, "chip_spec", lambda *a, **k: v5p)

    def fake_run_point(opts, mesh, nbytes, **kw):
        return _fake_point(opts.op, 1, [1e-5] * opts.num_runs)

    monkeypatch.setattr(bench, "run_point", fake_run_point, raising=False)
    monkeypatch.setattr(runner, "run_point", fake_run_point)
    bench.main()
    data = json.loads(capsys.readouterr().out.strip())
    by_name = {m["metric"].split("_p50")[0]: m for m in data["metrics"]}
    stream = by_name["hbm_stream_busbw"]
    assert stream["vs_baseline"] == pytest.approx(
        stream["value"] / v5p.stream_nominal_gbps, rel=1e-3)
    triad = by_name["hbm_triad_busbw"]
    assert triad["vs_baseline"] == pytest.approx(
        triad["value"] / v5p.triad_nominal_gbps, rel=1e-3)
    mxu = by_name["mxu_gemm_tflops"]
    assert mxu["vs_baseline"] == pytest.approx(
        mxu["value"] / v5p.mxu_nominal_tflops, rel=1e-3)
