"""Fleet observability plane (tpu_perf.fleet, `tpu-perf fleet`).

Covers the streaming readers' live-fleet tolerances (torn final line,
live .open tail, rotation/ingest races, quarantined files, two jobs
sharing a folder), the bounded-memory contract over a generated large
folder, cross-host MAD grading (the planted slow host is NAMED),
fleet-wide shift detection vs a baseline artifact, staleness gauges,
the fleet-*.log seventh-family round trip, heartbeat-anchored clock
alignment, multi-host timeline stitching, and the CLI surfaces
end to end.
"""

import glob
import io
import json
import os
import time

import pytest

from tpu_perf.config import Options
from tpu_perf.fleet import (
    FleetGradeConfig, align_spans, build_report, clock_offsets,
    discover_hosts, grade_hosts, read_fleet_records, render_textfile,
    report_to_json, report_to_markdown, stitch_hosts, stream_rows,
    write_fleet_records,
)
from tpu_perf.fleet.collect import host_paths, stream_parsed
from tpu_perf.fleet.report import collect_host
from tpu_perf.fleet.rollup import HostRollup, detect_shifts, fleet_medians
from tpu_perf.schema import EXT_PREFIX, ResultRow
from tpu_perf.trace import validate_chrome_trace


@pytest.fixture(scope="module")
def mesh(eight_devices):
    from tpu_perf.parallel import make_mesh

    return make_mesh((), ())


# ------------------------------------------------------------- helpers


def _row(job="job-a", op="ring", nbytes=32, lat_us=1000.0, run_id=1,
         mode="daemon", dtype="float32", **kw):
    return ResultRow(
        timestamp="2026-08-01 00:00:00.000", job_id=job, backend="jax",
        op=op, nbytes=nbytes, iters=1, run_id=run_id, n_devices=8,
        lat_us=lat_us, algbw_gbps=nbytes / lat_us / 1e3,
        busbw_gbps=nbytes / lat_us / 1e3, time_ms=lat_us / 1e3,
        dtype=dtype, mode=mode, **kw,
    )


def _write_log(folder, lines, *, prefix=EXT_PREFIX, job="job-a", rank=0,
               stamp="20260801-000000", suffix=""):
    os.makedirs(folder, exist_ok=True)
    path = os.path.join(folder,
                        f"{prefix}-{job}-{rank}-{stamp}.log{suffix}")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + ("\n" if lines else ""))
    return path


def _host_folder(root, host, lat_us, *, runs=30, job=None, mode="daemon"):
    folder = os.path.join(root, host)
    job = job or f"job-{host}"
    _write_log(folder, [
        _row(job=job, op="ring", nbytes=32, lat_us=lat_us, run_id=i,
             mode=mode).to_csv()
        for i in range(1, runs + 1)
    ], job=job)
    return folder


# ------------------------------------------------- streaming readers


def test_stream_rows_skips_header_and_torn_final_line(tmp_path, capsys):
    good = _row(run_id=1).to_csv()
    path = _write_log(str(tmp_path), [
        "timestamp,job_id,backend,op,nbytes", good,
        good[:-1],  # torn mid-field by a hard kill (empty last column)
    ])
    err = io.StringIO()
    rows = list(stream_rows([path], err=err))
    assert [r.run_id for r in rows] == [1]
    assert "torn final line" in err.getvalue()


def test_stream_rows_mid_file_corruption_raises(tmp_path):
    path = _write_log(str(tmp_path), [
        "garbage,line", _row(run_id=1).to_csv(),
    ])
    with pytest.raises(ValueError, match="garbage"):
        list(stream_rows([path], err=io.StringIO()))


def test_stream_reads_live_open_tail(tmp_path):
    path = _write_log(str(tmp_path), [_row(run_id=7).to_csv()],
                      suffix=".open")
    assert path.endswith(".log.open")
    rows = list(stream_rows([path], err=io.StringIO()))
    assert [r.run_id for r in rows] == [7]


def test_stream_rotated_mid_read_falls_back_to_closed_file(tmp_path):
    # the scan saw foo.log.open; the daemon closed (renamed) it before
    # the reader opened it — the finished file must be read instead
    closed = _write_log(str(tmp_path), [_row(run_id=3).to_csv()])
    err = io.StringIO()
    rows = list(stream_rows([closed + ".open"], err=err))
    assert [r.run_id for r in rows] == [3]
    assert "rotated mid-read" in err.getvalue()


def test_stream_vanished_file_is_skipped_with_note(tmp_path):
    err = io.StringIO()
    rows = list(stream_rows([str(tmp_path / "tpu-gone-0-x.log")], err=err))
    assert rows == []
    assert "vanished mid-read" in err.getvalue()


def test_quarantined_files_never_collected(tmp_path):
    folder = str(tmp_path)
    _write_log(folder, [_row(run_id=1).to_csv()])
    poison = _write_log(folder, ["poison"], stamp="20260801-000001")
    os.replace(poison, poison + ".quarantined")
    paths = host_paths(folder, EXT_PREFIX)
    assert len(paths) == 1 and not paths[0].endswith(".quarantined")
    # and the remaining file streams clean
    assert len(list(stream_rows(paths, err=io.StringIO()))) == 1


def test_stream_parsed_is_a_generator_not_a_list(tmp_path):
    path = _write_log(str(tmp_path), [_row(run_id=i).to_csv()
                                      for i in range(1, 4)])
    it = stream_parsed([path], lambda line: line, err=io.StringIO())
    assert next(it).startswith("2026-08-01")  # nothing pre-materialized


def test_two_jobs_sharing_a_folder_do_not_blend(tmp_path):
    folder = str(tmp_path / "host-a")
    # job A: clean daemon rows with adaptive columns; job B: a chaos
    # soak of the SAME point — distinct modes, distinct adaptive keys
    _write_log(folder, [
        _row(job="job-A", lat_us=1000.0, run_id=i,
             runs_requested=50, runs_taken=i, ci_rel=0.04).to_csv()
        for i in range(1, 11)
    ], job="job-A")
    _write_log(folder, [
        _row(job="job-B", lat_us=9000.0, run_id=i, mode="chaos",
             runs_requested=20, runs_taken=i, ci_rel=0.02).to_csv()
        for i in range(1, 6)
    ], job="job-B", stamp="20260801-000001")
    roll = collect_host("host-a", folder, err=io.StringIO())
    # the two jobs' curves never pool: mode separates them
    assert set(roll.points) == {("ring", 32, "float32", "daemon"),
                                ("ring", 32, "float32", "chaos")}
    assert roll.points[("ring", 32, "float32", "daemon")].runs == 10
    # adaptive verdicts are job-keyed: two rows, not one blended one
    assert {k[0] for k in roll.adaptive} == {"job-A", "job-B"}
    assert roll.adaptive[("job-A", "ring", 32, "float32")][
        "runs_requested"] == 50
    assert roll.adaptive[("job-B", "ring", 32, "float32")][
        "runs_requested"] == 20
    assert roll.jobs == {"job-A", "job-B"}


def test_discover_hosts_subfolders_and_single_folder_fallback(tmp_path):
    root = str(tmp_path)
    _host_folder(root, "host-a", 1000.0)
    _host_folder(root, "host-b", 1000.0)
    (tmp_path / "not-a-host").mkdir()
    assert sorted(discover_hosts(root)) == ["host-a", "host-b"]
    # a single record folder degrades to a one-host fleet
    single = discover_hosts(os.path.join(root, "host-a"))
    assert list(single) == ["host-a"]
    assert discover_hosts(str(tmp_path / "empty-nowhere")) == {}


# ------------------------------------------------- bounded memory


def test_large_folder_streams_with_bounded_memory(tmp_path):
    """The acceptance bar: peak memory is O(points), not O(rows) — a
    generated 150k-row folder collects under a ceiling two orders of
    magnitude below what retaining the rows would need."""
    import tracemalloc

    folder = str(tmp_path / "host-big")
    os.makedirs(folder)
    template = _row(lat_us=1000.0, run_id=1).to_csv()
    prefix, _, tail = template.partition(",ring,32,1,1,")
    n = 150_000
    for chunk in range(3):
        path = os.path.join(
            folder, f"tpu-job-big-0-2026080{chunk}-000000.log")
        with open(path, "w") as fh:
            fh.writelines(
                f"{prefix},ring,32,1,{i},{tail}\n"
                for i in range(chunk * n // 3 + 1,
                               (chunk + 1) * n // 3 + 1))
    tracemalloc.start()
    rep = build_report(folder, err=io.StringIO())
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    (roll,) = rep.hosts.values()
    assert roll.rows == n
    assert roll.points[("ring", 32, "float32", "daemon")].runs == n
    # 150k parsed rows retained would be tens of MB; the streaming
    # collector's peak stays under 8 MB regardless of row count
    assert peak < 8 * 1024 * 1024, f"peak {peak / 1e6:.1f} MB"


# ------------------------------------------------- cross-host grading


def _fleet(root, lats, **kw):
    for host, lat in lats.items():
        _host_folder(root, host, lat, **kw)


def test_grade_hosts_names_the_planted_slow_host(tmp_path):
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 1010.0, "host-c": 990.0,
                  "host-d": 3000.0})
    rep = build_report(root, err=io.StringIO())
    slow = [v for v in rep.verdicts if v.verdict != "ok"]
    assert [v.host for v in slow] == ["host-d"]
    assert rep.sick_hosts == ["host-d"]
    assert "peer host" in slow[0].detail
    # the ok hosts were still judged (the artifact records the
    # comparison, not just the alarms)
    assert {v.host for v in rep.verdicts} == {"host-a", "host-b",
                                              "host-c", "host-d"}


def test_grade_hosts_needs_min_hosts(tmp_path):
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 9000.0})
    rep = build_report(root, err=io.StringIO())
    assert rep.verdicts == []  # two hosts cannot outvote each other
    assert rep.sick_hosts == []


def test_chaos_rows_are_never_cross_host_graded(tmp_path):
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 1000.0, "host-c": 1000.0},
           mode="chaos")
    rep = build_report(root, err=io.StringIO())
    assert rep.verdicts == []
    assert rep.medians == []  # chaos stays out of the fleet medians too


def test_fleet_wide_shift_flagged_not_absorbed(tmp_path):
    """Every host 2x slower: each host's local baseline would absorb it
    and the cross-host MAD sees zero spread — the baseline-artifact
    comparison is the only instrument that can say 'the FLEET moved'."""
    base_root, cur_root = str(tmp_path / "base"), str(tmp_path / "cur")
    _fleet(base_root, {"host-a": 1000.0, "host-b": 1000.0,
                       "host-c": 1000.0})
    _fleet(cur_root, {"host-a": 2000.0, "host-b": 2000.0,
                      "host-c": 2000.0})
    base = build_report(base_root, err=io.StringIO())
    cur = build_report(cur_root, err=io.StringIO())
    shifts = detect_shifts(cur.medians, base.medians,
                           FleetGradeConfig())
    (shift,) = shifts
    assert shift.op == "ring" and 1.9 < shift.ratio < 2.1
    # and no host is blamed individually — the shift is fleet-scoped
    assert not [v for v in cur.verdicts if v.verdict != "ok"]


def test_fleet_medians_are_robust_to_one_straggler(tmp_path):
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 1000.0, "host-c": 9000.0})
    rep = build_report(root, err=io.StringIO())
    (m,) = [m for m in rep.medians if m["nbytes"] == 32]
    assert m["hosts"] == 3
    assert m["fleet_lat_p50_us"] == pytest.approx(1000.0, rel=0.01)


# ------------------------------------------------- staleness + textfile


def test_staleness_and_fleet_textfile(tmp_path):
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 1000.0})
    old = time.time() - 7200
    for p in glob.glob(os.path.join(root, "host-b", "*")):
        os.utime(p, (old, old))
    rep = build_report(root, err=io.StringIO())
    assert rep.stale_hosts == ["host-b"]
    text = render_textfile(rep)
    assert 'tpu_perf_fleet_host_stale{host="host-b"} 1' in text
    assert 'tpu_perf_fleet_host_stale{host="host-a"} 0' in text
    assert 'tpu_perf_fleet_host_last_seen_timestamp_seconds{host="host-b"}' \
        in text
    assert "tpu_perf_fleet_stale_hosts 1" in text
    assert "tpu_perf_fleet_last_report_timestamp_seconds" in text
    # markdown flags it too
    assert "STALE" in report_to_markdown(rep)


def test_rollup_output_folder_is_not_a_phantom_host(tmp_path):
    """`fleet report -l <root>/rollups` writes fleet-*.log INSIDE the
    fleet root; the next pass must not discover the collector's own
    output as a zero-row host (staleness gauges for a folder that was
    never a host)."""
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 1000.0, "host-c": 1000.0})
    rep = build_report(root, err=io.StringIO())
    write_fleet_records(os.path.join(root, "rollups"), rep,
                        job_id="fleet-job")
    assert sorted(discover_hosts(root)) == ["host-a", "host-b", "host-c"]


def test_cli_fleet_timeline_skips_a_corrupt_host(tmp_path, capsys):
    """One hard-killed host's mid-file span corruption must not blind
    the stitched view to the other hosts (the report collector's
    one-bad-host policy, applied to the timeline)."""
    from tpu_perf.cli import main

    root = str(tmp_path)
    _write_span_log(os.path.join(root, "host-a"),
                    _rank_spans("A", 0, 0), job="A", rank=0)
    bad = os.path.join(root, "host-b",
                       "spans-B-0-20260801-000000.log")
    os.makedirs(os.path.dirname(bad))
    with open(bad, "w") as fh:
        fh.write("{corrupt\n" + json.dumps(
            _span("B", 0, "run", "r1", 0, 10, run_id=1)) + "\n")
    out_path = str(tmp_path / "stitched.json")
    rc = main(["fleet", "timeline", root, "-o", out_path])
    out = capsys.readouterr()
    assert rc == 0
    assert "host-b" in out.err and "host skipped" in out.err
    data = json.load(open(out_path))
    assert validate_chrome_trace(data) == []
    procs = {e["args"]["name"] for e in data["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"host-a/rank 0"}


def test_host_with_no_records_is_not_a_host(tmp_path):
    # staleness is judged per discovered host; an empty subfolder is
    # not silently a "stale host" (it was never a host at all)
    root = str(tmp_path)
    _host_folder(root, "host-a", 1000.0)
    (tmp_path / "empty").mkdir()
    assert sorted(discover_hosts(root)) == ["host-a"]


# ------------------------------------------------- rollup records


def test_fleet_records_roundtrip_and_ingest_routing(tmp_path):
    root = str(tmp_path / "fleet")
    _fleet(root, {"host-a": 1000.0, "host-b": 1000.0, "host-c": 3000.0})
    rep = build_report(root, err=io.StringIO())
    outdir = str(tmp_path / "rollup")
    write_fleet_records(outdir, rep, job_id="fleet-job")
    (path,) = glob.glob(os.path.join(outdir, "fleet-*.log"))
    assert not path.endswith(".open")  # lazy close renamed it
    recs = read_fleet_records([path])
    kinds = [r["record"] for r in recs]
    assert kinds.count("meta") == 1
    assert kinds.count("host") == 3
    assert any(r["record"] == "verdict" and r["verdict"] == "slow"
               and r["host"] == "host-c" for r in recs)
    meta = next(r for r in recs if r["record"] == "meta")
    assert meta["sick_hosts"] == ["host-c"]
    # the seventh family rides the same ingest pass into its own sink
    from tpu_perf.ingest.pipeline import LocalDirBackend, run_all_ingest_passes

    sink = str(tmp_path / "sink")
    n = run_all_ingest_passes(outdir, backend=LocalDirBackend(sink))
    assert n == 1
    assert glob.glob(os.path.join(sink, "fleet-*.log"))
    assert not glob.glob(os.path.join(outdir, "fleet-*.log"))


# ------------------------------------------------- clock alignment


def _span(job, rank, kind, sid, t0, dur, **attrs):
    return {"record": "span", "job_id": job, "span_id": sid,
            "parent_id": None, "rank": rank, "thread": "main",
            "t_start_ns": t0, "dur_ns": dur, "kind": kind,
            "attrs": attrs}


def _rank_spans(job, rank, skew_ns):
    """One rank's spans on a clock offset by ``skew_ns``: heartbeat
    boundaries at shared barrier instants 10ms/20ms, runs between."""
    out = []
    sid = 0
    for rid, barrier in ((20, 10_000_000), (40, 20_000_000)):
        sid += 1
        out.append(_span(job, rank, "run", f"r{sid}",
                         barrier - 500_000 - skew_ns, 400_000,
                         run_id=rid, op="ring", nbytes=32))
        sid += 1
        out.append(_span(job, rank, "heartbeat", f"m{sid}",
                         barrier - 100_000 - skew_ns, 100_000,
                         run_id=rid, window=rid // 20 - 1))
    return out


def test_clock_offsets_from_heartbeat_anchors():
    spans = _rank_spans("J", 0, 0) + _rank_spans("J", 1, 5_000_000)
    offsets = clock_offsets(spans, err=io.StringIO())
    assert offsets == {("J", 0): 0, ("J", 1): 5_000_000}
    aligned = align_spans(spans, offsets)
    ends = {}
    for s in aligned:
        if s["kind"] == "heartbeat" and s["attrs"]["run_id"] == 20:
            ends[s["rank"]] = s["t_start_ns"] + s["dur_ns"]
    assert ends[0] == ends[1]  # the shared barrier instant
    # originals untouched
    assert {s["t_start_ns"] for s in spans} != \
        {s["t_start_ns"] for s in aligned}


def test_clock_offsets_run_span_fallback(capsys):
    spans = [s for s in _rank_spans("J", 0, 0) + _rank_spans("J", 1, 3_000_000)
             if s["kind"] == "run"]
    err = io.StringIO()
    offsets = clock_offsets(spans, err=err)
    assert offsets[("J", 1)] == 3_000_000
    assert "approximate" in err.getvalue()


def test_clock_offsets_never_cross_jobs():
    # two independent jobs share no anchors and no clock: both stay raw
    spans = _rank_spans("A", 0, 0) + _rank_spans("B", 0, 7_000_000)
    offsets = clock_offsets(spans, err=io.StringIO())
    assert offsets == {("A", 0): 0, ("B", 0): 0}


def test_stitch_hosts_separates_same_rank_processes():
    host_spans = {
        "host-a": _rank_spans("A", 0, 0),
        "host-b": _rank_spans("B", 0, 0),
    }
    spans, names = stitch_hosts(host_spans, err=io.StringIO())
    assert sorted(names.values()) == ["host-a/rank 0", "host-b/rank 0"]
    assert {s["rank"] for s in spans} == {0, 1}
    from tpu_perf.trace import to_chrome_trace

    data = to_chrome_trace(spans, names)
    assert validate_chrome_trace(data) == []
    procs = {e["args"]["name"] for e in data["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"host-a/rank 0", "host-b/rank 0"}


def test_stitch_aligns_one_job_across_host_folders():
    # a distributed job's ranks land in different host folders; the
    # stitcher still aligns them (same job_id ⇒ shared anchors)
    host_spans = {
        "host-a": _rank_spans("J", 0, 0),
        "host-b": _rank_spans("J", 1, 4_000_000),
    }
    spans, _ = stitch_hosts(host_spans, err=io.StringIO())
    ends = {s["rank"]: s["t_start_ns"] + s["dur_ns"] for s in spans
            if s["kind"] == "heartbeat" and s["attrs"]["run_id"] == 20}
    assert ends[0] == ends[1]


# ------------------------------------------------- driver heartbeat spans


def test_driver_emits_heartbeat_anchor_spans(mesh):
    opts = Options(op="ring", sweep="8", iters=1, num_runs=12,
                   fence="block", synthetic_s=1e-3, fault_seed=7,
                   uuid="job-hb", spans=True, stats_every=5)
    from tpu_perf.driver import Driver

    d = Driver(opts, mesh, err=io.StringIO())
    d.run()
    hbs = [s for s in d.tracer.records if s["kind"] == "heartbeat"]
    assert [s["attrs"]["run_id"] for s in hbs] == [5, 10]
    assert [s["attrs"]["window"] for s in hbs] == [0, 1]
    assert all(s["attrs"]["collective"] is False for s in hbs)
    # nested under the boundary run's span (the run is the barrier)
    by_id = {s["span_id"]: s for s in d.tracer.records}
    assert all(by_id[s["parent_id"]]["kind"] == "run" for s in hbs)


def test_heartbeat_spans_survive_daemon_sampling():
    from tpu_perf.spans import SAMPLE_KEEP_KINDS, SpanTracer

    assert "heartbeat" in SAMPLE_KEEP_KINDS
    tr = SpanTracer("job", retain=True,
                    perf_ns=iter(range(1000)).__next__, sample=3)
    with tr.span("sweep"):
        with tr.run_span(2):  # (2-1) % 3 != 0: sampled OUT
            with tr.span("heartbeat", run_id=2):
                pass
            with tr.span("fence"):
                pass
    kinds = [s["kind"] for s in tr.records]
    assert "heartbeat" in kinds and "fence" not in kinds


# ----------------------------------------------------------------- CLI


def test_cli_fleet_report_end_to_end(tmp_path, capsys):
    from tpu_perf.cli import main

    root = str(tmp_path / "fleet")
    _fleet(root, {"host-a": 1000.0, "host-b": 1010.0, "host-c": 3000.0})
    art = str(tmp_path / "fleet.json")
    prom = str(tmp_path / "fleet.prom")
    rc = main(["fleet", "report", root, "-o", art, "--textfile", prom])
    out = capsys.readouterr()
    assert rc == 9  # the sick host fails the gate
    assert "host-c" in out.err and "graded sick" in out.err
    assert "| host-c | ring |" in out.out
    data = json.load(open(art))
    assert data["summary"]["sick_hosts"] == ["host-c"]
    assert any(v["verdict"] == "slow" for v in data["verdicts"])
    with open(prom) as fh:
        assert 'tpu_perf_fleet_host_sick{host="host-c"} 1' in fh.read()


def test_cli_fleet_report_json_and_healthy_exit(tmp_path, capsys):
    from tpu_perf.cli import main

    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0, "host-b": 1000.0, "host-c": 1005.0})
    rc = main(["fleet", "report", root, "--format", "json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["summary"]["sick_hosts"] == []
    assert len(data["curves"]) == 3


def test_cli_fleet_report_baseline_shift_gate(tmp_path, capsys):
    from tpu_perf.cli import main

    base_root, cur_root = str(tmp_path / "b"), str(tmp_path / "c")
    _fleet(base_root, {"host-a": 1000.0, "host-b": 1000.0,
                       "host-c": 1000.0})
    _fleet(cur_root, {"host-a": 2000.0, "host-b": 2000.0,
                      "host-c": 2000.0})
    art = str(tmp_path / "base.json")
    assert main(["fleet", "report", base_root, "-o", art]) == 0
    capsys.readouterr()
    rc = main(["fleet", "report", cur_root, "--baseline", art])
    out = capsys.readouterr()
    assert rc == 9
    assert "fleet-wide shift" in out.err.lower() or \
        "Fleet-wide shifts" in out.out
    assert "sick (none)" in out.out  # no host blamed individually


def test_cli_fleet_report_stale_gate_and_empty_root(tmp_path, capsys):
    from tpu_perf.cli import main

    assert main(["fleet", "report", str(tmp_path / "nothing")]) == 1
    root = str(tmp_path)
    _fleet(root, {"host-a": 1000.0})
    old = time.time() - 7200
    for p in glob.glob(os.path.join(root, "host-a", "*")):
        os.utime(p, (old, old))
    capsys.readouterr()
    assert main(["fleet", "report", root]) == 0  # stale alone: report
    assert main(["fleet", "report", root, "--fail-on-stale"]) == 9


def test_cli_fleet_report_validates_knobs_before_walking(tmp_path):
    from tpu_perf.cli import main

    assert main(["fleet", "report", str(tmp_path), "--min-hosts", "1"]) \
        == 2


def _write_span_log(folder, spans, *, job, rank):
    os.makedirs(folder, exist_ok=True)
    path = os.path.join(folder,
                        f"spans-{job}-{rank}-20260801-000000.log")
    with open(path, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s, sort_keys=True) + "\n")
    return path


def test_cli_timeline_aligns_skewed_ranks_in_one_folder(tmp_path, capsys):
    """The single-host bugfix: two processes of one job launched
    seconds apart merge onto one clock (heartbeat anchors), unless
    --no-align asks for raw clocks."""
    from tpu_perf.cli import main

    folder = str(tmp_path)
    _write_span_log(folder, _rank_spans("J", 0, 0), job="J", rank=0)
    _write_span_log(folder, _rank_spans("J", 1, 5_000_000), job="J",
                    rank=1)

    def heartbeat_ends(argv):
        assert main(argv) == 0
        data = json.loads(capsys.readouterr().out)
        return {e["pid"]: e["ts"] + e["dur"]
                for e in data["traceEvents"]
                if e.get("cat") == "heartbeat"
                and e["args"]["run_id"] == 20}

    aligned = heartbeat_ends(["timeline", folder])
    assert aligned[0] == aligned[1]
    raw = heartbeat_ends(["timeline", folder, "--no-align"])
    assert abs(raw[0] - raw[1]) == pytest.approx(5000.0)  # µs of skew


def test_cli_fleet_timeline_stitches_and_checks(tmp_path, capsys):
    from tpu_perf.cli import main

    root = str(tmp_path)
    _write_span_log(os.path.join(root, "host-a"),
                    _rank_spans("J", 0, 0), job="J", rank=0)
    _write_span_log(os.path.join(root, "host-b"),
                    _rank_spans("J", 1, 2_000_000), job="J", rank=1)
    out_path = str(tmp_path / "stitched.json")
    rc = main(["fleet", "timeline", root, "-o", out_path])
    assert rc == 0
    assert "2 host(s)" in capsys.readouterr().err
    data = json.load(open(out_path))
    assert validate_chrome_trace(data) == []
    procs = {e["args"]["name"] for e in data["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"host-a/rank 0", "host-b/rank 1"}
    assert main(["fleet", "timeline", str(tmp_path / "nowhere")]) == 1


def test_cli_fleet_timeline_end_to_end_with_driver_folders(mesh, tmp_path,
                                                           capsys):
    """Real span folders (synthetic driver soaks on two 'hosts') stitch
    into one valid trace with complete joins."""
    from tpu_perf.cli import main
    from tpu_perf.driver import Driver

    root = tmp_path / "fleet"
    for host in ("host-a", "host-b"):
        opts = Options(op="ring", sweep="8", iters=1, num_runs=8,
                       fence="block", synthetic_s=1e-3, fault_seed=7,
                       uuid=f"job-{host}", spans=True, stats_every=4,
                       logfolder=str(root / host))
        Driver(opts, mesh, err=io.StringIO()).run()
    out_path = str(tmp_path / "stitched.json")
    rc = main(["fleet", "timeline", str(root), "--check", "-o", out_path])
    err = capsys.readouterr().err
    assert rc == 0
    assert err.count("join complete") == 2
    data = json.load(open(out_path))
    assert validate_chrome_trace(data) == []
    cats = {e.get("cat") for e in data["traceEvents"]}
    assert "heartbeat" in cats and "run" in cats
