"""Detectors, events, and the exporter (tpu_perf.health.detect/events/
exporter): pure-python units, no mesh or jax involvement."""

import json
import math
import os

import pytest

from tpu_perf.health.detect import (
    HealthConfig, PointDetector, capture_loss_finding,
)
from tpu_perf.health.events import (
    HealthEvent, events_to_json, events_to_markdown, read_events,
    summarize_events,
)
from tpu_perf.health.exporter import (
    PointGauges, TextfileExporter, render_textfile,
)

CFG = HealthConfig(threshold=0.5, warmup=10, flatline_run=5)


def _noisy(base, i, scale=1e-6):
    """Deterministic jitter: timings never repeat bit-identically."""
    return base + scale * (math.sin(i * 12.9898) * 0.5 + 0.5)


def test_config_validation():
    with pytest.raises(ValueError):
        HealthConfig(threshold=0.0)
    with pytest.raises(ValueError):
        HealthConfig(spike_z=-1.0)
    with pytest.raises(ValueError):
        HealthConfig(warmup=0)
    with pytest.raises(ValueError):
        HealthConfig(flatline_run=1)
    with pytest.raises(ValueError):
        HealthConfig(drop_rate=0.0)
    with pytest.raises(ValueError):
        HealthConfig(ewma_alpha=2.0)


def test_clean_series_no_findings():
    d = PointDetector(CFG)
    for i in range(200):
        assert d.observe(_noisy(1.0, i)) == []


def test_no_findings_during_warmup():
    # wild values inside the warm-up window shape the baseline silently
    d = PointDetector(CFG)
    for i, x in enumerate((1.0, 50.0, 0.1, 30.0, 1.0, 2.0, 9.0, 1.0, 4.0)):
        assert d.observe(_noisy(x, i)) == []


def test_step_regression_fires_exactly_once():
    d = PointDetector(CFG)
    for i in range(30):
        assert d.observe(_noisy(1.0, i)) == []
    findings = []
    for i in range(30, 60):
        findings += d.observe(_noisy(2.0, i))  # the injected 2x step
    kinds = [f.kind for f in findings]
    assert kinds == ["regression"]  # one event, not one per run
    (f,) = findings
    assert f.severity in ("warning", "critical")
    assert f.observed > f.baseline * 1.5
    assert d.regressed


def test_regression_recovers_with_hysteresis():
    d = PointDetector(CFG)
    for i in range(30):
        d.observe(_noisy(1.0, i))
    for i in range(30, 45):
        d.observe(_noisy(2.0, i))
    assert d.regressed
    findings = []
    for i in range(45, 90):
        findings += d.observe(_noisy(1.0, i))
    assert [f.kind for f in findings] == ["recovered"]
    assert not d.regressed


def test_regression_escalates_to_critical_as_ewma_converges():
    """A step big enough to be critical at its converged level but not
    at the entry instant (the EWMA has only partly converged when the
    event fires) must escalate in place — once — not stay warning."""
    d = PointDetector(CFG)
    for i in range(30):
        d.observe(_noisy(1.0, i))
    findings = []
    for i in range(30, 60):
        findings += d.observe(_noisy(2.5, i))  # converged rel = 1.5 > 1.0
    assert [f.kind for f in findings] == ["regression", "regression"]
    assert [f.severity for f in findings] == ["warning", "critical"]
    # recovery resets the escalation for the next episode
    for i in range(60, 100):
        d.observe(_noisy(1.0, i))
    assert not d.regressed


def test_large_step_is_critical():
    d = PointDetector(CFG)
    for i in range(30):
        d.observe(_noisy(1.0, i))
    findings = []
    for i in range(30, 40):
        findings += d.observe(_noisy(4.0, i))  # 4x >> 2*threshold
    assert [f.kind for f in findings] == ["regression"]
    assert findings[0].severity == "critical"


def test_sustained_regression_does_not_self_heal():
    """The frozen-baseline contract: degraded samples must not drift the
    long-run median up to the degraded level and fire a false recovery
    while the link is still slow."""
    d = PointDetector(CFG)
    for i in range(100):
        d.observe(_noisy(1.0, i))
    findings = []
    for i in range(100, 500):
        findings += d.observe(_noisy(2.0, i))  # a PERMANENT 2x step
    assert [f.kind for f in findings] == ["regression"]  # never "recovered"
    assert d.regressed
    # genuine recovery still fires, judged against the CLEAN baseline
    findings = []
    for i in range(500, 540):
        findings += d.observe(_noisy(1.0, i))
    assert [f.kind for f in findings] == ["recovered"]
    assert not d.regressed


def test_flatline_exit_emits_recovered():
    d = PointDetector(CFG)
    for i in range(20):
        d.observe(_noisy(1.0, i))
    findings = []
    for _ in range(10):
        findings += d.observe(1.0)
    assert [f.kind for f in findings] == ["flatline"]
    assert [f.kind for f in d.observe(_noisy(1.0, 99))] == ["recovered"]
    assert not d.flatlined


def test_isolated_spike_fires_and_step_does_not_spike():
    d = PointDetector(CFG)
    for i in range(50):
        d.observe(_noisy(1.0, i))
    # the spike sample itself is judged only when its successor returns
    # to baseline (consecutive high samples are a step, not a spike)
    assert d.observe(10.0) == []
    findings = d.observe(_noisy(1.0, 51))
    assert [f.kind for f in findings] == ["spike"]
    assert findings[0].observed == 10.0
    assert not d.regressed


def test_flatline_fires_once_and_rearms():
    d = PointDetector(CFG)
    for i in range(20):
        d.observe(_noisy(1.0, i))
    findings = []
    for _ in range(20):
        findings += d.observe(1.0)  # bit-identical: a stuck clock
    assert [f.kind for f in findings] == ["flatline"]
    assert d.flatlined
    d.observe(_noisy(1.0, 99))  # movement re-arms
    assert not d.flatlined


def test_capture_loss_finding_thresholds():
    cfg = HealthConfig(drop_rate=0.25)
    assert capture_loss_finding(0, 100, cfg) is None
    assert capture_loss_finding(10, 100, cfg) is None  # 10% <= 25%
    warn = capture_loss_finding(30, 100, cfg)
    assert warn.kind == "capture_loss" and warn.severity == "warning"
    assert warn.observed == pytest.approx(0.3)
    crit = capture_loss_finding(60, 100, cfg)
    assert crit.severity == "critical"
    assert capture_loss_finding(0, 0, cfg) is None
    # with drop_rate >= 0.5 the doubled bar saturates at 1.0 — total
    # capture loss must still reach critical, not cap out at warning
    total = capture_loss_finding(100, 100, HealthConfig(drop_rate=0.5))
    assert total.severity == "critical"


# --- events ---------------------------------------------------------------


def _event(**kw):
    base = dict(
        timestamp="2026-01-01 00:00:00.000", job_id="job", kind="regression",
        severity="warning", op="ring", nbytes=64, dtype="float32",
        run_id=10, window=1, observed=2.0, baseline=1.0, unit="s",
    )
    base.update(kw)
    return HealthEvent(**base)


def test_event_json_round_trip():
    ev = _event()
    line = ev.to_json()
    assert json.loads(line)["kind"] == "regression"
    assert HealthEvent.from_json(line) == ev
    # the duck-typed row interface rides RotatingCsvLog.write_row
    assert ev.to_csv() == line


def test_event_from_json_rejects_garbage():
    with pytest.raises(ValueError):
        HealthEvent.from_json('["not", "an", "object"]')
    with pytest.raises(ValueError):
        HealthEvent.from_json('{"kind": "regression"}')  # missing fields


def test_read_events_skips_blank_lines(tmp_path):
    p = tmp_path / "health-u-0-x.log"
    p.write_text(_event().to_json() + "\n\n" + _event(run_id=11).to_json() + "\n")
    events = read_events([str(p)])
    assert [e.run_id for e in events] == [10, 11]


def test_summarize_events_groups_and_ranks():
    events = [
        _event(run_id=10), _event(run_id=30, severity="critical"),
        _event(run_id=20),
        _event(op="halo", kind="spike", severity="warning", run_id=5),
        _event(op="ring", nbytes=0, kind="capture_loss", severity="info",
               run_id=40, unit="drop_rate"),
    ]
    summaries = summarize_events(events)
    assert [s.kind for s in summaries] == [
        "regression", "spike", "capture_loss",  # worst severity first
    ]
    reg = summaries[0]
    assert (reg.count, reg.first_run, reg.last_run) == (3, 10, 30)
    assert reg.severity == "critical"  # worst of the group
    md = events_to_markdown(summaries)
    assert "| regression |" in md and "| capture_loss |" in md
    assert "| — |" in md  # nbytes=0 renders as op-level
    raw = json.loads(events_to_json(events))
    assert len(raw) == 5 and raw[0]["op"] == "ring"


# --- exporter -------------------------------------------------------------


def test_render_textfile_families_and_labels():
    pts = [PointGauges(op="ring", nbytes=64, dtype="float32", samples=100,
                       lat_p50_us=12.5, lat_p99_us=20.0, busbw_gbps=3.5,
                       severity="warning")]
    text = render_textfile(pts, {"ring": 0.1}, {"regression": 2})
    assert '# TYPE tpu_perf_health_lat_p50_us gauge' in text
    assert ('tpu_perf_health_lat_p50_us{op="ring",nbytes="64",'
            'dtype="float32"} 12.5') in text
    assert 'tpu_perf_health_point_severity{' in text and '} 1' in text
    assert 'tpu_perf_health_drop_rate{op="ring"} 0.1' in text
    assert 'tpu_perf_health_events_total{kind="regression"} 2' in text
    assert text.endswith("\n")


def test_textfile_exporter_atomic_write(tmp_path):
    path = tmp_path / "metrics" / "tpu-perf.prom"
    exp = TextfileExporter(str(path))
    exp.write([], {}, {})
    assert path.exists()
    assert not os.path.exists(str(path) + ".tmp")  # temp file renamed away
    first = path.read_text()
    exp.write([], {"ring": 0.5}, {})
    assert path.read_text() != first
