"""Live telemetry push plane (ISSUE 12): the bounded tee queue +
background sender (tpu_perf.push), its sinks (NDJSON HTTP routing +
live Prometheus textfile), the dead-letter spool riding the ingest
quarantine contract, the inertness guarantee (push off / on ⇒
byte-identical chaos ledgers), the streaming single-host report, and
the `fleet report --drain-hook` sick-host action.
"""

import glob
import io
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_perf.config import Options
from tpu_perf.driver import Driver, RotatingCsvLog
from tpu_perf.faults import FaultSpec
from tpu_perf.fleet.drain import (
    DRAIN_STATE_FILE, load_drain_state, run_drain_hooks, save_drain_state,
)
from tpu_perf.health.events import read_events
from tpu_perf.ingest.pipeline import (
    QUARANTINE_SUFFIX, list_quarantined, requeue_quarantined,
)
from tpu_perf.parallel import make_mesh
from tpu_perf.push import (
    DEFAULT_QUEUE, NULL_PUSHER, HttpSink, PushError, PushPlane,
    PUSH_ROUTES, TEE_FREE_FAMILIES, live_spool_files, parse_spool_family,
    plane_from_options, push_records_once, read_spool,
    render_push_textfile, spool_depth, write_spool,
)
from tpu_perf.push import spool as spool_mod
from tpu_perf.schema import (
    ALL_PREFIXES, CHAOS_PREFIX, EXT_PREFIX, HEALTH_PREFIX, LEGACY_PREFIX,
    ResultRow, SPANS_PREFIX,
)


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


# ----------------------------------------------------------- helpers


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class ListSink:
    """In-process sink with a scriptable failure window."""

    def __init__(self):
        self.fail = False
        self.batches = []

    def send(self, family, lines):
        if self.fail:
            raise PushError("sink down")
        self.batches.append((family, list(lines)))

    @property
    def lines(self):
        return [ln for _, batch in self.batches for ln in batch]


def _plane(tmp_path, sink=None, **kw):
    kw.setdefault("clock", FakeClock())
    kw.setdefault("jitter", lambda: 0.5)  # delay = base * 2^(n-1) * 1.0
    return PushPlane(
        [sink] if sink is not None else [], job_id="job-p", rank=0,
        spool_dir=str(tmp_path), start=False, err=io.StringIO(), **kw)


class _Collector:
    """Loopback http.server sink: records every NDJSON POST per
    (path, family header); scriptable to refuse (500) or tear the
    connection mid-request."""

    def __init__(self):
        self.got = {}
        self.mode = "ok"
        collector = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()
                if collector.mode == "tear":
                    # close the socket without any response: the
                    # client sees a torn connection, not an HTTP error
                    self.connection.close()
                    return
                if collector.mode == "refuse":
                    self.send_response(500)
                    self.end_headers()
                    return
                fam = self.headers.get("X-TpuPerf-Family", "?")
                collector.got.setdefault((self.path, fam), []).extend(
                    body.splitlines())
                self.send_response(204)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)
        self.thread.start()

    def lines(self, family):
        return [ln for (path, fam), v in self.got.items()
                if fam == family for ln in v]

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def collector():
    c = _Collector()
    yield c
    c.close()


# ------------------------------------------------ plane: queue + drops


def test_null_pusher_is_inert():
    assert NULL_PUSHER.enabled is False
    assert NULL_PUSHER.tee_for(EXT_PREFIX) is None
    NULL_PUSHER.tee(EXT_PREFIX, "row")  # no-op, no state
    assert NULL_PUSHER.totals() is None
    NULL_PUSHER.close()


def test_plane_from_options_defaults_to_null():
    assert plane_from_options(Options(op="ring")) is NULL_PUSHER


def test_overflow_drops_are_counted_and_noted(tmp_path):
    p = _plane(tmp_path, ListSink(), maxlen=5, drop_note_every=1000)
    for i in range(8):
        p.tee(EXT_PREFIX, f"row{i}")
    t = p.totals()
    assert t["dropped"] == 3 and t["queued"] == 5
    assert "queue full" in p.err.getvalue()  # noted, not silent
    p.close()


def test_tee_never_accepts_the_chaos_ledger(tmp_path):
    p = _plane(tmp_path, ListSink())
    assert p.tee_for(CHAOS_PREFIX) is None  # even asked directly
    p.tee(CHAOS_PREFIX, "ledger-line")      # and the raw tee refuses
    p._cycle()
    assert p.totals()["sent"] == 0 and p.totals()["queued"] == 0
    p.close()


def test_delivery_batches_per_family(tmp_path):
    sink = ListSink()
    p = _plane(tmp_path, sink)
    p.tee(EXT_PREFIX, "a")
    p.tee(HEALTH_PREFIX, "h")
    p.tee(EXT_PREFIX, "b")
    p._cycle()
    assert sorted(sink.batches) == [(HEALTH_PREFIX, ["h"]),
                                    (EXT_PREFIX, ["a", "b"])]
    t = p.totals()
    assert t["sent"] == 3 and t["queued"] == 0
    p.close()


# -------------------------------------------- plane: backoff schedule


def test_backoff_schedule_from_injected_clock(tmp_path):
    """Exponential: 0.25, 0.5, 1.0, ... capped at backoff_max, with
    the injected jitter pinned to the midpoint (factor 1.0)."""
    sink = ListSink()
    sink.fail = True
    clk = FakeClock()
    p = _plane(tmp_path, sink, clock=clk, max_attempts=10,
               backoff_max=0.8)
    p.tee(EXT_PREFIX, "a")
    delays = []
    for _ in range(4):
        p._cycle()
        delays.append(round(p._next_try - clk.t, 6))
        clk.t = p._next_try
    assert delays == [0.25, 0.5, 0.8, 0.8]  # doubled, then capped
    assert p.totals()["retried"] == 4
    # between retries the sender does NOT hammer the sink
    before = p.totals()["retried"]
    clk.t = p._next_try - 0.01
    p._cycle()
    assert p.totals()["retried"] == before
    # recovery resets the schedule
    sink.fail = False
    clk.t = p._next_try
    p._cycle()
    assert p.totals()["sent"] == 1 and p._attempts == 0
    p.close()


def test_exhausted_retries_dead_letter_to_quarantined_spool(tmp_path):
    sink = ListSink()
    sink.fail = True
    clk = FakeClock()
    p = _plane(tmp_path, sink, clock=clk, max_attempts=3)
    p.tee(EXT_PREFIX, "a")
    p.tee(EXT_PREFIX, "b")
    for _ in range(3):
        p._cycle()
        clk.t = max(clk.t, p._next_try)
    t = p.totals()
    assert t["spooled"] == 2 and t["spool_depth"] == 1
    (path,) = list_quarantined(str(tmp_path))
    assert parse_spool_family(path) == EXT_PREFIX
    assert read_spool(path) == ["a", "b"]
    p.close()


def test_backlog_beyond_queue_bound_spools_mid_backoff(tmp_path):
    """An outage longer than the backoff covers must not grow memory
    without bound: pending past the queue bound dead-letters early."""
    sink = ListSink()
    sink.fail = True
    clk = FakeClock()
    p = _plane(tmp_path, sink, clock=clk, maxlen=4, max_attempts=100)
    for i in range(4):
        p.tee(EXT_PREFIX, f"r{i}")
    p._cycle()          # absorb + first failed flush -> backoff
    for i in range(4, 8):
        p.tee(EXT_PREFIX, f"r{i}")
    p._cycle()          # still backing off; pending 8 > maxlen 4
    t = p.totals()
    assert t["spooled"] == 8 and t["queued"] == 0 and t["dropped"] == 0
    p.close()


def test_requeued_spool_replays_to_revived_sink(tmp_path):
    sink = ListSink()
    sink.fail = True
    clk = FakeClock()
    p = _plane(tmp_path, sink, clock=clk, max_attempts=1)
    p.tee(HEALTH_PREFIX, '{"kind":"spike"}')
    p._cycle()  # one attempt -> dead-lettered quarantined
    assert p.totals()["spooled"] == 1
    assert live_spool_files(str(tmp_path)) == []  # quarantined: not live
    restored = requeue_quarantined(str(tmp_path))
    assert len(restored) == 1
    sink.fail = False
    clk.t += 1000.0
    p._cycle()  # healthy + idle -> replays the live spool
    t = p.totals()
    assert t["replayed"] == 1 and t["sent"] == 1
    assert t["spool_depth"] == 0  # deleted only after delivery
    assert sink.batches == [(HEALTH_PREFIX, ['{"kind":"spike"}'])]
    p.close()


def test_requeued_spool_replays_even_while_records_flow(tmp_path):
    """A busy daemon (records in every flush window) must still drain a
    requeued spool: replay runs on any healthy cycle, not only on the
    soak's first record-free one."""
    sink = ListSink()
    sink.fail = True
    clk = FakeClock()
    p = _plane(tmp_path, sink, clock=clk, max_attempts=1)
    p.tee(EXT_PREFIX, "dead")
    p._cycle()  # dead-lettered
    requeue_quarantined(str(tmp_path))
    sink.fail = False
    clk.t += 1000.0
    p.tee(EXT_PREFIX, "live")  # the cycle is NOT idle
    p._cycle()
    t = p.totals()
    assert t["replayed"] == 1 and t["sent"] == 2
    assert t["spool_depth"] == 0
    p.close()


def test_live_spool_listing_tolerates_concurrent_delete(tmp_path,
                                                        monkeypatch):
    """A concurrent replayer deleting a spool between listdir and stat
    must not raise out of live_spool_files (it would kill the sender
    thread for the rest of the soak)."""
    doomed = write_spool(str(tmp_path), EXT_PREFIX, "job", 0, ["x"],
                         seq=1, quarantine=False)
    survivor = write_spool(str(tmp_path), HEALTH_PREFIX, "job", 0, ["y"],
                           seq=2, quarantine=False)
    real_getmtime = os.path.getmtime

    def racing_getmtime(path):
        if path == doomed and os.path.exists(doomed):
            os.remove(doomed)
            raise FileNotFoundError(doomed)
        return real_getmtime(path)

    monkeypatch.setattr(os.path, "getmtime", racing_getmtime)
    assert live_spool_files(str(tmp_path)) == [(survivor, HEALTH_PREFIX)]


def test_close_flushes_then_spools_remainder(tmp_path):
    sink = ListSink()
    p = _plane(tmp_path, sink)
    p.tee(EXT_PREFIX, "flushed")
    p.close()
    assert sink.lines == ["flushed"]
    sink2 = ListSink()
    sink2.fail = True
    p2 = _plane(tmp_path, sink2)
    p2.tee(EXT_PREFIX, "stranded")
    p2.close()  # final attempt fails -> dead-lettered, never lost
    assert p2.totals()["spooled"] == 1
    p2.close()  # idempotent


def test_queue_bound_validation():
    with pytest.raises(ValueError, match="queue bound"):
        PushPlane([], job_id="j", maxlen=0, start=False)


# --------------------------------------------------------------- sinks


def test_push_routes_partition_all_families():
    """Every rotating family is routed xor tee-free (the contract lint
    R3 proves at parse time, pinned here at runtime too)."""
    for fam in ALL_PREFIXES:
        assert (fam in PUSH_ROUTES) != (fam in TEE_FREE_FAMILIES)
    assert TEE_FREE_FAMILIES == (CHAOS_PREFIX,)


def test_http_sink_routing_mirrors_kusto_tables():
    from tpu_perf.ingest.pipeline import HEALTH_TABLE, TPU_TABLE

    s = HttpSink("http://h:1/")
    assert s.endpoint(EXT_PREFIX) == f"http://h:1/v1/{TPU_TABLE}"
    assert s.endpoint(HEALTH_PREFIX) == f"http://h:1/v1/{HEALTH_TABLE}"
    with pytest.raises(PushError, match="no push route"):
        s.endpoint(CHAOS_PREFIX)


def test_http_sink_loopback_routing(collector):
    sink = HttpSink(collector.url)
    sink.send(EXT_PREFIX, ["row1", "row2"])
    sink.send(HEALTH_PREFIX, ['{"kind":"spike"}'])
    assert collector.got[("/v1/PerfLogsTPU", EXT_PREFIX)] == [
        "row1", "row2"]
    assert collector.got[("/v1/HealthEventsTPU", HEALTH_PREFIX)] == [
        '{"kind":"spike"}']


def test_http_sink_torn_connection_is_retryable(collector, tmp_path):
    """A connection the server tears mid-request surfaces as PushError
    (the sender's retry unit), and the plane redelivers the SAME batch
    once the sink heals — at-least-once, no loss."""
    collector.mode = "tear"
    sink = HttpSink(collector.url)
    with pytest.raises(PushError):
        sink.send(EXT_PREFIX, ["torn"])
    clk = FakeClock()
    p = _plane(tmp_path, sink, clock=clk)
    p.tee(EXT_PREFIX, "torn-then-delivered")
    p._cycle()
    assert p.totals()["retried"] == 1 and p.totals()["sent"] == 0
    collector.mode = "ok"
    clk.t = p._next_try
    p._cycle()
    assert p.totals()["sent"] == 1
    assert collector.lines(EXT_PREFIX) == ["torn-then-delivered"]
    p.close()


def test_http_sink_5xx_is_retryable(collector):
    collector.mode = "refuse"
    with pytest.raises(PushError):
        HttpSink(collector.url).send(EXT_PREFIX, ["r"])


def test_push_records_once_is_loud_never_fatal(tmp_path):
    err = io.StringIO()
    ok = push_records_once("http://127.0.0.1:1", HEALTH_PREFIX,
                           ["rec"], err=err)
    assert ok is False
    assert "could not push" in err.getvalue()
    assert push_records_once("http://127.0.0.1:1", HEALTH_PREFIX, [],
                             err=err) is True  # nothing to push


def test_render_push_textfile_carries_meters_and_families():
    text = render_push_textfile(
        {EXT_PREFIX: 7}, {"sent": 7, "dropped": 1, "retried": 2,
                          "spooled": 0, "replayed": 0, "queued": 3,
                          "spool_depth": 0, "backoff": 1})
    assert "tpu_perf_push_sent_total 7" in text
    assert "tpu_perf_push_dropped_total 1" in text
    assert "tpu_perf_push_backoff 1" in text
    assert ('tpu_perf_push_family_sent_total{family="tpu"} 7'
            in text)


# --------------------------------------------------------------- spool


def test_spool_name_round_trip():
    name = spool_mod.spool_name(SPANS_PREFIX, "job-a-b", 3, 12)
    assert parse_spool_family(name) == SPANS_PREFIX
    assert parse_spool_family(name + QUARANTINE_SUFFIX) == SPANS_PREFIX
    assert parse_spool_family("tpu-job-0-x.log") is None
    assert parse_spool_family("push-nonfamily-j-0-000001.spool") is None


def test_spool_lives_in_quarantine_triage_surface(tmp_path):
    """`ingest --list-quarantined` lists dead-lettered push batches
    next to poison ingest files: one triage surface for both planes."""
    path = write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["x"], seq=1)
    assert path.endswith(QUARANTINE_SUFFIX)
    assert list_quarantined(str(tmp_path)) == [path]
    assert spool_depth(str(tmp_path)) == 1


def test_requeue_refuses_to_clobber_live_spool(tmp_path, capsys):
    live = write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["live"],
                       seq=1, quarantine=False)
    write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["dead"], seq=1)
    assert requeue_quarantined(str(tmp_path)) == []
    assert "not requeueing" in capsys.readouterr().err
    assert read_spool(live) == ["live"]  # untouched
    assert spool_depth(str(tmp_path)) == 2


def test_spool_seq_collision_disambiguates_not_overwrites(tmp_path):
    a = write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["a"], seq=1)
    b = write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["b"], seq=1)
    assert a != b and read_spool(a) == ["a"] and read_spool(b) == ["b"]
    # the disambiguated name stays on every recovery surface: triage,
    # requeue, the depth gauge, and (once requeued) replay
    assert sorted(list_quarantined(str(tmp_path))) == sorted([a, b])
    assert spool_depth(str(tmp_path)) == 2
    assert parse_spool_family(b) == EXT_PREFIX
    assert len(requeue_quarantined(str(tmp_path))) == 2
    lives = spool_mod.live_spool_files(str(tmp_path))
    assert len(lives) == 2 and {f for _, f in lives} == {EXT_PREFIX}


def test_spool_files_never_match_family_scans(tmp_path):
    from tpu_perf.fleet.collect import host_paths

    write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["x"], seq=1,
                quarantine=False)
    for fam in ALL_PREFIXES:
        assert host_paths(str(tmp_path), fam) == []


# --------------------------------------------------- options / config


def test_push_queue_without_push_is_loud():
    with pytest.raises(ValueError, match="push_queue"):
        Options(op="ring", push_queue=50)
    with pytest.raises(ValueError, match="push_queue"):
        Options(op="ring", push_queue=-1, push_url="http://x")
    # --push-textfile alone builds a sink-less plane that tees nothing,
    # so the queue the knob sizes is never consulted: loud, not inert
    with pytest.raises(ValueError, match="push_queue"):
        Options(op="ring", push_queue=50, push_textfile="x.prom")


def test_push_needs_the_jax_record_plane():
    with pytest.raises(ValueError, match="push plane"):
        Options(op="allreduce", backend="mpi", push_url="http://x")


def test_plane_from_options_builds_http_sink(tmp_path):
    opts = Options(op="ring", push_url="http://127.0.0.1:9",
                   push_queue=77, logfolder=str(tmp_path))
    p = plane_from_options(opts, rank=1)
    try:
        assert p.enabled and p._maxlen == 77
        assert p.spool_dir == str(tmp_path)
        assert isinstance(p.sinks[0], HttpSink)
        assert p.textfile is None  # no --push-textfile
    finally:
        p.close()
    opts2 = Options(op="ring", push_textfile=str(tmp_path / "p.prom"))
    p2 = plane_from_options(opts2, rank=1)  # non-zero rank: no textfile
    try:
        assert p2.enabled and p2.sinks == [] and p2.textfile is None
    finally:
        p2.close()
    q = plane_from_options(opts2, rank=0)
    try:
        assert q.textfile is not None
        assert q._maxlen == DEFAULT_QUEUE
    finally:
        q.close()


# ------------------------------------------------------- driver wiring


def _push_opts(folder, url, **kw):
    base = dict(op="ring", sweep="8,32", iters=1, num_runs=4,
                fence="block", synthetic_s=1e-3, uuid="job-push",
                logfolder=str(folder), push_url=url)
    base.update(kw)
    return Options(**base)


def test_textfile_only_plane_never_tees(tmp_path):
    """A sink-less plane (--push-textfile alone) is a pure live-meter
    surface: it tees nothing, so `sent` can never claim deliveries
    that had nowhere to go."""
    p = _plane(tmp_path)  # no sink
    assert p.tee_for(EXT_PREFIX) is None
    p.tee(EXT_PREFIX, "x")
    p._cycle()
    t = p.totals()
    assert t["sent"] == 0 and t["queued"] == 0 and t["dropped"] == 0


def test_driver_soak_delivers_every_family_live(mesh, tmp_path,
                                                collector):
    opts = _push_opts(tmp_path, collector.url, spans=True, health=True,
                      push_textfile=str(tmp_path / "push.prom"))
    d = Driver(opts, mesh, err=io.StringIO())
    rows = d.run()
    t = d.pusher.totals()
    assert t["dropped"] == 0 and t["queued"] == 0 and t["sent"] > 0
    # every durable row reached the sink, bytes intact
    (log,) = glob.glob(str(tmp_path / "tpu-*.log"))
    with open(log) as fh:
        durable = fh.read().splitlines()
    assert collector.lines(EXT_PREFIX) == durable
    assert len(collector.lines(LEGACY_PREFIX)) == len(rows)
    # spans flowed too: every delivered span is a durable span (the
    # log is the source of truth; the tee only ever echoes it), the
    # run spans made it out live, and the sender's own `push` spans
    # are in the durable taxonomy
    span_lines = collector.lines(SPANS_PREFIX)
    assert span_lines
    (slog,) = glob.glob(str(tmp_path / "spans-*.log"))
    with open(slog) as fh:
        durable_spans = fh.read().splitlines()
    assert set(span_lines) <= set(durable_spans)
    delivered_kinds = {json.loads(ln)["kind"] for ln in span_lines}
    durable_kinds = {json.loads(ln)["kind"] for ln in durable_spans}
    assert "run" in delivered_kinds
    assert "push" in durable_kinds
    # live textfile refreshed by the sender, not the rotation
    with open(tmp_path / "push.prom") as fh:
        prom = fh.read()
    assert "tpu_perf_push_sent_total" in prom
    assert "tpu_perf_push_dropped_total 0" in prom
    # the sidecar carries the cumulative counters for the report
    (side,) = glob.glob(str(tmp_path / "phase-*.json"))
    with open(side) as fh:
        push = json.load(fh)["push"]
    assert push["dropped"] == 0 and push["sent"] == t["sent"]


def test_driver_off_holds_null_pusher(mesh, tmp_path):
    d = Driver(_push_opts(tmp_path, None), mesh, err=io.StringIO())
    assert d.pusher is NULL_PUSHER
    d.run()
    (side,) = glob.glob(str(tmp_path / "phase-*.json"))
    with open(side) as fh:
        assert "push" not in json.load(fh)  # push-off sidecars unchanged


def test_chaos_ledger_byte_identical_push_on_vs_off(mesh, tmp_path,
                                                    collector):
    """The determinism guard: a seeded chaos soak's ledger (and rows)
    are byte-identical with the plane on vs off — the tee is an
    observer, never a participant, and the ledger is never teed."""
    faults = [FaultSpec(kind="spike", op="ring", nbytes=32, start=2,
                        end=3, magnitude=30.0)]
    outs = {}
    for mode in ("off", "on"):
        folder = tmp_path / mode
        url = collector.url if mode == "on" else None
        opts = _push_opts(folder, url, faults=faults, fault_seed=11)
        Driver(opts, mesh, err=io.StringIO()).run()
        (ledger,) = glob.glob(str(folder / "chaos-*.log"))
        with open(ledger) as fh:
            outs[mode, "ledger"] = fh.read()
        (log,) = glob.glob(str(folder / "tpu-*.log"))
        with open(log) as fh:
            outs[mode, "rows"] = [",".join(ln.split(",")[1:])
                                  for ln in fh.read().splitlines()]
    assert outs["on", "ledger"] == outs["off", "ledger"]
    assert outs["on", "rows"] == outs["off", "rows"]
    # and the ledger was never POSTed anywhere
    assert collector.lines(CHAOS_PREFIX) == []


def test_driver_heartbeat_json_carries_push_counters(mesh, tmp_path,
                                                     collector):
    err = io.StringIO()
    opts = _push_opts(tmp_path, collector.url, stats_every=2,
                      heartbeat_format="json")
    Driver(opts, mesh, err=err).run()
    beats = [json.loads(ln) for ln in err.getvalue().splitlines()
             if ln.startswith("{") and '"heartbeat"' in ln]
    assert beats
    for b in beats:
        assert set(b["push"]) >= {"sent", "dropped", "retried",
                                  "spooled", "replayed", "queued",
                                  "spool_depth", "backoff"}
    # push-off heartbeats stay byte-compatible (no push key)
    err2 = io.StringIO()
    opts2 = _push_opts(tmp_path / "off", None, stats_every=2,
                       heartbeat_format="json")
    Driver(opts2, mesh, err=err2).run()
    beats2 = [json.loads(ln) for ln in err2.getvalue().splitlines()
              if ln.startswith("{") and '"heartbeat"' in ln]
    assert beats2 and all("push" not in b for b in beats2)


def test_sink_outage_mid_soak_spools_and_replays(mesh, tmp_path,
                                                 collector):
    """The acceptance scenario's middle act: sink dies mid-soak, the
    plane dead-letters, requeue + a healthy plane replays — zero
    silent loss end to end."""
    collector.mode = "refuse"
    opts = _push_opts(tmp_path, collector.url)
    d = Driver(opts, mesh, err=io.StringIO())
    # fast schedule so the 4-run soak exhausts retries deterministically
    d.pusher.max_attempts = 1
    d.pusher.backoff_base = 0.0
    d.run()
    t = d.pusher.totals()
    assert t["spooled"] > 0 and t["spool_depth"] > 0
    assert t["sent"] == 0
    # requeue the dead letters, then replay to the revived sink
    requeue_quarantined(str(tmp_path))
    collector.mode = "ok"
    from tpu_perf.cli import main

    rc = main(["push", "replay", str(tmp_path), "--url", collector.url])
    assert rc == 0
    (log,) = glob.glob(str(tmp_path / "tpu-*.log"))
    with open(log) as fh:
        durable = fh.read().splitlines()
    assert sorted(collector.lines(EXT_PREFIX)) == sorted(durable)
    assert spool_depth(str(tmp_path)) == 0


# ------------------------------------------------------ streaming report


def _write_rows(folder, rows, *, job="job-a", rank=0,
                stamp="20260801-000000"):
    os.makedirs(folder, exist_ok=True)
    path = os.path.join(folder, f"tpu-{job}-{rank}-{stamp}.log")
    with open(path, "w") as fh:
        fh.writelines(r.to_csv() + "\n" for r in rows)
    return path


def _row(op="ring", nbytes=32, lat_us=1000.0, run_id=1, **kw):
    return ResultRow(
        timestamp="2026-08-01 00:00:00.000", job_id=kw.pop("job", "job-a"),
        backend="jax", op=op, nbytes=nbytes, iters=1, run_id=run_id,
        n_devices=8, lat_us=lat_us, algbw_gbps=nbytes / lat_us / 1e3,
        busbw_gbps=nbytes / lat_us / 1e3, time_ms=lat_us / 1e3,
        dtype="float32", mode="daemon", **kw)


def test_stream_aggregate_identical_to_buffered(tmp_path):
    from tpu_perf.report import (
        aggregate, collect_paths, read_rows, stream_aggregate,
        to_json, to_markdown,
    )

    rows = [_row(op=op, nbytes=nb, lat_us=1000.0 + 7 * i, run_id=i,
                 algo=algo, skew_us=skew)
            for op in ("ring", "exchange") for nb in (8, 32)
            for algo, skew in (("", 0), ("bruck", 0), ("", 250))
            for i in range(1, 6)]
    _write_rows(str(tmp_path), rows)
    paths = collect_paths(str(tmp_path))
    buffered = aggregate(read_rows(paths))
    streamed = stream_aggregate(paths)
    assert streamed == buffered  # exact, not approximate
    assert to_markdown(streamed) == to_markdown(buffered)
    assert to_json(streamed) == to_json(buffered)


def test_stream_aggregate_tolerates_torn_final_line(tmp_path, capsys):
    from tpu_perf.report import stream_aggregate

    path = _write_rows(str(tmp_path), [_row(run_id=i)
                                       for i in range(1, 4)])
    with open(path, "a") as fh:
        fh.write("2026-08-01 00:00:01.000,job-a,jax,ring,32")  # torn
    pts = stream_aggregate([path])
    assert [p.runs for p in pts] == [3]
    assert "torn final line" in capsys.readouterr().err


def test_stream_aggregate_bounded_memory_150k_rows(tmp_path):
    """The large-folder pin: 150k rows aggregate in O(samples-as-
    doubles), never rows-as-objects — the same bound the fleet
    collector holds."""
    import tracemalloc

    n = 150_000
    template = _row(job="job-big", run_id=999999999).to_csv()
    assert template.count("999999999") == 1
    path = os.path.join(str(tmp_path), "tpu-job-big-0-20260801-000000.log")
    with open(path, "w") as fh:
        fh.writelines(template.replace("999999999", str(i)) + "\n"
                      for i in range(1, n + 1))
    from tpu_perf.report import stream_aggregate

    tracemalloc.start()
    pts = stream_aggregate([path])
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert [p.runs for p in pts] == [n]
    assert peak < 16 * 1024 * 1024, f"peak {peak / 1e6:.1f} MB"


def test_stream_adaptive_savings_identical_to_buffered(tmp_path):
    from tpu_perf.report import (
        adaptive_savings, collect_paths, read_rows,
        stream_adaptive_savings,
    )

    rows = [_row(op="ring", nbytes=32, run_id=i, runs_requested=30,
                 runs_taken=i, ci_rel=0.5 / i) for i in range(1, 12)]
    rows += [_row(op="ring", nbytes=8, run_id=i) for i in range(1, 4)]
    _write_rows(str(tmp_path), rows)
    paths = collect_paths(str(tmp_path))
    assert stream_adaptive_savings(paths) == \
        adaptive_savings(read_rows(paths))


def test_report_renders_push_plane_table(tmp_path, capsys):
    from tpu_perf.cli import main

    _write_rows(str(tmp_path), [_row(run_id=i) for i in range(1, 4)])
    with open(tmp_path / "phase-job-a-0.json", "w") as fh:
        json.dump({"job_id": "job-a", "rank": 0, "wall_s": 1.0,
                   "phase": {"compile_s": 0.1},
                   "push": {"sent": 55, "dropped": 1, "retried": 2,
                            "spooled": 3, "replayed": 3,
                            "spool_depth": 0}}, fh)
    assert main(["report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "### Push plane" in out
    assert "| job-a | 0 | 55 | 1 | 2 | 3 | 3 | 0 |" in out
    # a push-off folder renders no push table
    off = tmp_path / "off"
    _write_rows(str(off), [_row(run_id=i) for i in range(1, 4)])
    with open(off / "phase-job-a-0.json", "w") as fh:
        json.dump({"job_id": "job-a", "rank": 0, "wall_s": 1.0,
                   "phase": {"compile_s": 0.1}}, fh)
    assert main(["report", str(off)]) == 0
    assert "### Push plane" not in capsys.readouterr().out


# ----------------------------------------------------------- drain hook


class FakeRunner:
    def __init__(self, rc=0, raise_=None):
        self.calls = []
        self.rc = rc
        self.raise_ = raise_

    def __call__(self, argv, *, env=None, timeout=None,
                 capture_output=False, text=False):
        self.calls.append((argv, env["TPU_PERF_SICK_HOST"]))
        assert capture_output and text  # stdout must never be inherited
        if self.raise_:
            raise self.raise_

        class P:
            returncode = self.rc
            stdout = "hook says hi"
            stderr = ""

        return P()


def test_drain_hook_fires_once_per_sick_host(tmp_path):
    runner = FakeRunner()
    outs = run_drain_hooks(
        str(tmp_path), ["host-c", "host-a", "host-c"], "kubectl drain",
        now=100.0, err=io.StringIO(), runner=runner)
    assert [(o.host, o.action) for o in outs] == [
        ("host-a", "invoked"), ("host-c", "invoked")]  # deduped, sorted
    assert [env for _, env in runner.calls] == ["host-a", "host-c"]
    assert runner.calls[0][0] == ["/bin/sh", "-c",
                                  "kubectl drain host-a"]
    state = load_drain_state(str(tmp_path))
    assert state == {"host-a": 100.0, "host-c": 100.0}


def test_drain_hook_rate_limited_per_host(tmp_path):
    save_drain_state(str(tmp_path), {"host-a": 100.0})
    runner = FakeRunner()
    outs = run_drain_hooks(
        str(tmp_path), ["host-a", "host-b"], "drain", interval=3600.0,
        now=200.0, err=io.StringIO(), runner=runner)
    assert [(o.host, o.action) for o in outs] == [
        ("host-a", "rate-limited"), ("host-b", "invoked")]
    assert [env for _, env in runner.calls] == ["host-b"]
    # past the interval the host drains again
    outs2 = run_drain_hooks(
        str(tmp_path), ["host-a"], "drain", interval=3600.0,
        now=100.0 + 3601.0, err=io.StringIO(), runner=runner)
    assert outs2[0].action == "invoked"


def test_drain_hook_failure_is_reported_and_rate_limited(tmp_path):
    err = io.StringIO()
    runner = FakeRunner(rc=3)
    (out,) = run_drain_hooks(str(tmp_path), ["host-x"], "drain",
                             now=5.0, err=err, runner=runner)
    assert out.action == "failed" and out.rc == 3
    assert "FAILED" in err.getvalue()
    # a broken hook is NOT hammered every pass: the state updated
    runner2 = FakeRunner()
    (out2,) = run_drain_hooks(str(tmp_path), ["host-x"], "drain",
                              now=6.0, err=io.StringIO(),
                              runner=runner2)
    assert out2.action == "rate-limited" and runner2.calls == []
    # an exec exception is a failure too, never a raise
    runner3 = FakeRunner(raise_=OSError("no such file"))
    (out3,) = run_drain_hooks(str(tmp_path), ["host-y"], "drain",
                              now=7.0, err=io.StringIO(),
                              runner=runner3)
    assert out3.action == "failed" and "no such file" in out3.error


def test_drain_hook_spans_and_quoting(tmp_path):
    from tpu_perf.spans import SpanTracer

    tracer = SpanTracer("job-d", rank=0, retain=True)
    runner = FakeRunner(rc=1)
    run_drain_hooks(str(tmp_path), ["host a"], "drain", now=1.0,
                    err=io.StringIO(), runner=runner, tracer=tracer)
    assert runner.calls[0][0][2] == "drain 'host a'"  # shell-quoted
    (span,) = [s for s in tracer.records if s["kind"] == "drain_hook"]
    assert span["attrs"]["host"] == "host a"
    assert span["attrs"]["error"] is True


def _sick_fleet(root):
    """Three hosts, one planted slow: the 0i construction in miniature."""
    for host, lat in (("host-a", 1000.0), ("host-b", 1010.0),
                      ("host-c", 3000.0)):
        _write_rows(os.path.join(root, host),
                    [_row(job=f"job-{host}", lat_us=lat, run_id=i)
                     for i in range(1, 31)], job=f"job-{host}")


def test_cli_fleet_report_drain_hook_e2e(tmp_path, capsys):
    """`fleet report --drain-hook` invokes the command exactly once per
    sick host (TPU_PERF_SICK_HOST + quoted argument), records drain
    records in the fleet log, and a second pass is rate-limited."""
    from tpu_perf.cli import main
    from tpu_perf.fleet import read_fleet_records

    root = str(tmp_path / "fleet")
    _sick_fleet(root)
    hits = str(tmp_path / "hits.txt")
    logs = str(tmp_path / "logs")
    hook = f"echo drained >> {hits} && printenv TPU_PERF_SICK_HOST >> {hits}"
    rc = main(["fleet", "report", root, "-l", logs,
               "--drain-hook", f"sh -c '{hook}' --"])
    err = capsys.readouterr().err
    assert rc == 9  # the verdict is unchanged by the hook
    assert "drain hook invoked for host-c" in err
    with open(hits) as fh:
        assert fh.read().splitlines() == ["drained", "host-c"]
    # the drain outcome landed in the rollup family next to the verdict
    (flog,) = glob.glob(os.path.join(logs, "fleet-*.log"))
    recs = read_fleet_records([flog])
    drains = [r for r in recs if r["record"] == "drain"]
    assert [(d["host"], d["action"]) for d in drains] == [
        ("host-c", "invoked")]
    # spans: the hook execution is auditable in the trace
    (slog,) = glob.glob(os.path.join(logs, "spans-*.log"))
    with open(slog) as fh:
        kinds = [json.loads(ln)["kind"] for ln in fh]
    assert kinds.count("drain_hook") == 1
    # second pass inside the interval: rate-limited, hook NOT re-run
    rc2 = main(["fleet", "report", root, "-l", logs,
                "--drain-hook", f"sh -c '{hook}' --"])
    err2 = capsys.readouterr().err
    assert rc2 == 9 and "rate-limited" in err2
    with open(hits) as fh:
        assert len(fh.read().splitlines()) == 2  # unchanged
    assert os.path.exists(os.path.join(root, DRAIN_STATE_FILE))


def test_cli_fleet_report_drain_hook_failure_health_evented(tmp_path,
                                                            capsys):
    from tpu_perf.cli import main

    root = str(tmp_path / "fleet")
    _sick_fleet(root)
    logs = str(tmp_path / "logs")
    rc = main(["fleet", "report", root, "-l", logs,
               "--drain-hook", "exit 7 ; true"])
    assert rc == 9
    assert "drain hook FAILED" in capsys.readouterr().err
    (hlog,) = glob.glob(os.path.join(logs, "health-*.log"))
    events = read_events([hlog])
    fails = [e for e in events if e.kind == "drain_fail"]
    assert [e.op for e in fails] == ["drain:host-c"]
    assert fails[0].severity == "critical"


def test_cli_fleet_report_healthy_fleet_never_drains(tmp_path, capsys):
    from tpu_perf.cli import main

    root = str(tmp_path / "fleet")
    for host in ("host-a", "host-b", "host-c"):
        _write_rows(os.path.join(root, host),
                    [_row(job=f"job-{host}", lat_us=1000.0, run_id=i)
                     for i in range(1, 31)], job=f"job-{host}")
    hits = str(tmp_path / "hits.txt")
    rc = main(["fleet", "report", root,
               "--drain-hook", f"touch {hits}"])
    assert rc == 0
    assert not os.path.exists(hits)
    assert not os.path.exists(os.path.join(root, DRAIN_STATE_FILE))


# ------------------------------------------------------- push replay CLI


def test_cli_push_replay_delivers_and_deletes(tmp_path, collector,
                                              capsys):
    from tpu_perf.cli import main

    write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["r1", "r2"], seq=1,
                quarantine=False)
    write_spool(str(tmp_path), HEALTH_PREFIX, "j", 0, ['{"k":1}'],
                seq=2, quarantine=False)
    rc = main(["push", "replay", str(tmp_path), "--url", collector.url])
    assert rc == 0
    assert collector.lines(EXT_PREFIX) == ["r1", "r2"]
    assert collector.lines(HEALTH_PREFIX) == ['{"k":1}']
    assert spool_depth(str(tmp_path)) == 0
    assert "2 spool file(s) replayed" in capsys.readouterr().err


def test_cli_push_replay_failure_keeps_file(tmp_path, collector,
                                            capsys):
    from tpu_perf.cli import main

    collector.mode = "refuse"
    path = write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["kept"],
                       seq=1, quarantine=False)
    rc = main(["push", "replay", str(tmp_path), "--url", collector.url])
    assert rc == 1
    assert os.path.exists(path)  # delete only after acceptance
    assert "FAILED" in capsys.readouterr().err


def test_cli_push_replay_points_at_requeue_for_quarantined(tmp_path,
                                                           capsys):
    from tpu_perf.cli import main

    write_spool(str(tmp_path), EXT_PREFIX, "j", 0, ["dead"], seq=1)
    rc = main(["push", "replay", str(tmp_path), "--url",
               "http://127.0.0.1:1"])
    assert rc == 0  # nothing live to replay is not a failure
    err = capsys.readouterr().err
    assert "no live spool files" in err and "--requeue" in err


# -------------------------------------------------- rotating-log tee


def test_rotating_log_tee_sees_exact_bytes_after_write(tmp_path):
    teed = []
    log = RotatingCsvLog(str(tmp_path), "job-t", 0, refresh_sec=10**9,
                         prefix=EXT_PREFIX, tee=teed.append)
    row = _row()
    log.write_row(row)
    log.close()
    (path,) = glob.glob(str(tmp_path / "tpu-*.log"))
    with open(path) as fh:
        assert fh.read() == teed[0] + "\n"
    assert teed == [row.to_csv()]
