"""Span tracing (ISSUE 6): the tracer, the sixth rotating family, the
cross-family joins, the Chrome-trace timeline export, and the inertness
contract (tracing off ⇒ byte-identical rows and chaos ledgers)."""

import glob
import io
import json
import os

import pytest

from tpu_perf.config import Options
from tpu_perf.driver import Driver
from tpu_perf.faults import FaultSpec
from tpu_perf.health.events import HealthEvent, read_events
from tpu_perf.parallel import make_mesh
from tpu_perf.schema import ResultRow
from tpu_perf.spans import (
    NULL_TRACER, SpanRecord, SpanTracer, read_span_records,
)
from tpu_perf.trace import (
    anomaly_context, anomaly_to_markdown, build_measure_overlaps,
    chrome_trace_json, join_completeness, resolve_run_span,
    to_chrome_trace, validate_chrome_trace, write_timeline,
)


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


class FakeNs:
    """Deterministic perf_ns: +1 µs per call."""

    def __init__(self):
        self.t = 0

    def __call__(self):
        self.t += 1000
        return self.t


class FakeClock:
    """Deterministic seconds clock (drives Driver clock + perf_clock)."""

    def __init__(self):
        self.t = 1_700_000_000.0

    def __call__(self):
        self.t += 1e-4
        return self.t


# -- tracer unit behavior -----------------------------------------------


def test_span_record_roundtrip():
    rec = SpanRecord(record="span", job_id="j", span_id="m1",
                     parent_id=None, rank=0, thread="main",
                     t_start_ns=5, dur_ns=7, kind="run",
                     attrs={"run_id": 1})
    back = SpanRecord.from_json(rec.to_json())
    assert back.data == rec.data


def test_tracer_nesting_parentage_and_deterministic_ids():
    tr = SpanTracer("job", rank=3, retain=True, perf_ns=FakeNs())
    with tr.span("job") as j:
        with tr.span("point", op="ring", nbytes=8) as p:
            with tr.run_span(1, op="ring", nbytes=8) as r:
                pass
            assert r == "r1"
        assert p == "m2"
    assert j == "m1"
    recs = {s["span_id"]: s for s in tr.records}
    assert recs["r1"]["parent_id"] == "m2"
    assert recs["m2"]["parent_id"] == "m1"
    assert recs["m1"]["parent_id"] is None
    assert recs["r1"]["kind"] == "run"
    assert recs["r1"]["attrs"] == {"op": "ring", "nbytes": 8, "run_id": 1}
    assert recs["r1"]["rank"] == 3 and recs["r1"]["thread"] == "main"
    # records close innermost-first with start/duration from the fake
    # clock — never wall clock
    assert recs["m1"]["t_start_ns"] < recs["m2"]["t_start_ns"]
    # a second tracer replays the identical ID stream (the determinism
    # contract: (job_id, rank, counter), no wall clock, no RNG)
    tr2 = SpanTracer("job", rank=3, retain=True, perf_ns=FakeNs())
    with tr2.span("job"):
        with tr2.span("point", op="ring", nbytes=8):
            with tr2.run_span(1, op="ring", nbytes=8):
                pass
    assert [s["span_id"] for s in tr2.records] == \
        [s["span_id"] for s in tr.records]


def test_run_span_lane_is_unique_across_point_restarts():
    # finite sweeps restart run_id per point; the r-lane counter keeps
    # span ids unique anyway
    tr = SpanTracer("job", retain=True, perf_ns=FakeNs())
    for _ in range(2):  # two points, run_id 1 each
        with tr.run_span(1, op="ring", nbytes=8):
            pass
    ids = [s["span_id"] for s in tr.records]
    assert ids == ["r1", "r2"]


def test_error_spans_are_marked_and_closed():
    tr = SpanTracer("job", retain=True, perf_ns=FakeNs())
    with pytest.raises(RuntimeError):
        with tr.span("build", op="ring"):
            raise RuntimeError("boom")
    (rec,) = tr.records
    assert rec["attrs"]["error"] is True


def test_wrap_hook_spans_success_and_failure():
    tr = SpanTracer("job", retain=True, perf_ns=FakeNs())
    calls = []
    ok = tr.wrap_hook(lambda: calls.append(1))
    ok()
    def bad():
        raise OSError("down")
    with pytest.raises(OSError):
        tr.wrap_hook(bad)()
    kinds = [(s["kind"], s["attrs"].get("error")) for s in tr.records]
    assert kinds == [("ingest_hook", None), ("ingest_hook", True)]
    assert calls == [1]


def test_null_tracer_is_inert():
    assert NULL_TRACER.enabled is False
    with NULL_TRACER.span("anything", op="x") as sid:
        assert sid == ""
    with NULL_TRACER.run_span(1) as sid:
        assert sid == ""
    hook = lambda: None  # noqa: E731
    assert NULL_TRACER.wrap_hook(hook) is hook
    assert NULL_TRACER.wrap_hook(None) is None
    NULL_TRACER.emit("rotate", 0, 0)
    NULL_TRACER.maybe_rotate()
    NULL_TRACER.close()


# -- schema: the optional span column -----------------------------------


def _row(**kw):
    base = dict(timestamp="t", job_id="j", backend="jax", op="ring",
                nbytes=8, iters=1, run_id=1, n_devices=8, lat_us=1.0,
                algbw_gbps=1.0, busbw_gbps=1.0, time_ms=1.0)
    base.update(kw)
    return ResultRow(**base)


def test_result_row_span_column_only_when_traced():
    untraced = _row()
    assert len(untraced.to_csv().split(",")) == 18  # pre-span bytes
    traced = _row(span_id="r7")
    line = traced.to_csv()
    assert len(line.split(",")) == 19
    assert ResultRow.from_csv(line).span_id == "r7"
    assert ResultRow.from_csv(untraced.to_csv()).span_id == ""


def test_old_row_field_counts_still_parse():
    line19 = _row(span_id="r7").to_csv()
    line18 = _row().to_csv()
    parts = line18.split(",")
    for n in (12, 13, 15, 18, 19):
        line = line19 if n == 19 else ",".join(parts[:n])
        row = ResultRow.from_csv(line)
        assert row.op == "ring" and row.nbytes == 8
    with pytest.raises(ValueError):
        ResultRow.from_csv(",".join(parts[:14]))


def test_health_event_span_field_optional():
    ev = HealthEvent(timestamp="t", job_id="j", kind="spike",
                     severity="warning", op="ring", nbytes=8,
                     dtype="float32", run_id=3, window=0, observed=2.0,
                     baseline=1.0)
    assert "span_id" not in json.loads(ev.to_json())  # pre-span bytes
    traced = HealthEvent(**{**json.loads(ev.to_json()), "span_id": "r3"})
    data = json.loads(traced.to_json())
    assert data["span_id"] == "r3"
    assert HealthEvent.from_json(traced.to_json()).span_id == "r3"
    assert HealthEvent.from_json(ev.to_json()).span_id == ""


# -- driver wiring -------------------------------------------------------


def _synthetic_opts(tmp_path=None, **kw):
    base = dict(op="ring,exchange", sweep="8,32", iters=1, num_runs=4,
                fence="block", synthetic_s=1e-3, fault_seed=7,
                uuid="job-fixed", spans=True)
    if tmp_path is not None:
        base["logfolder"] = str(tmp_path)
    base.update(kw)
    return Options(**base)


def test_driver_stamps_rows_and_emits_span_family(mesh, tmp_path):
    d = Driver(_synthetic_opts(tmp_path, health=True), mesh,
               err=io.StringIO())
    rows = d.run()
    assert rows and all(r.span_id for r in rows)
    (slog,) = glob.glob(str(tmp_path / "spans-*.log"))
    spans = read_span_records([slog])
    kinds = {s["kind"] for s in spans}
    assert {"job", "sweep", "point", "run", "measure", "build",
            "warmup"} <= kinds
    # rows round-trip with the span column and join exactly
    (log,) = glob.glob(str(tmp_path / "tpu-*.log"))
    with open(log) as fh:
        parsed = [ResultRow.from_csv(ln) for ln in fh.read().splitlines()]
    assert [r.span_id for r in parsed] == [r.span_id for r in rows]
    assert join_completeness(spans, rows=parsed) == []
    # parentage: every run span sits under a point span under the sweep
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        if s["kind"] == "run":
            assert by_id[s["parent_id"]]["kind"] == "point"


def test_driver_spans_off_is_byte_identical_minus_span_column(mesh, tmp_path):
    faults = [FaultSpec(kind="spike", op="ring", nbytes=32, start=2,
                        end=3, magnitude=30.0)]
    outs = {}
    for mode in ("off", "on"):
        folder = tmp_path / mode
        opts = _synthetic_opts(folder, spans=(mode == "on"), faults=faults)
        Driver(opts, mesh, err=io.StringIO()).run()
        (log,) = glob.glob(str(folder / "tpu-*.log"))
        with open(log) as fh:
            rows = fh.read().splitlines()
        (ledger,) = glob.glob(str(folder / "chaos-*.log"))
        with open(ledger) as fh:
            outs[mode, "ledger"] = fh.read()
        outs[mode, "rows"] = rows
    # the chaos ledger is byte-identical with spans on vs off: the
    # tracer writes its own family only
    assert outs["on", "ledger"] == outs["off", "ledger"]
    # rows differ ONLY by the trailing span column (timestamps are wall
    # clock, so compare the stable fields)
    strip = [",".join(ln.split(",")[1:18]) for ln in outs["on", "rows"]]
    off = [",".join(ln.split(",")[1:]) for ln in outs["off", "rows"]]
    assert strip == off
    assert all(len(ln.split(",")) == 19 for ln in outs["on", "rows"])
    assert all(len(ln.split(",")) == 18 for ln in outs["off", "rows"])


def test_timeline_export_is_byte_stable_with_injected_clocks(mesh):
    def export_once():
        opts = _synthetic_opts()  # no logfolder: records retained
        d = Driver(opts, mesh, clock=FakeClock(), perf_clock=FakeClock(),
                   err=io.StringIO())
        d.run()
        assert d.tracer.records
        return chrome_trace_json(d.tracer.records)

    assert export_once() == export_once()  # the golden-file contract


def test_chrome_trace_structure_and_tracks():
    tr = SpanTracer("job", retain=True, perf_ns=FakeNs())
    with tr.span("sweep"):
        with tr.run_span(1, op="ring", nbytes=8):
            pass
        t0 = tr.now()
        tr.emit("ingest_hook", t0, 10)
    data = to_chrome_trace(tr.records)
    assert validate_chrome_trace(data) == []
    x = [e for e in data["traceEvents"] if e["ph"] == "X"]
    by_name = {e["name"]: e for e in x}
    assert by_name["run:ring"]["tid"] == 0          # main track
    assert by_name["ingest_hook"]["tid"] == 2       # its own track
    meta = [e for e in data["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta} >= {"rank 0", "main",
                                                 "ingest-hook"}
    assert validate_chrome_trace({"traceEvents": []})
    assert validate_chrome_trace([1, 2])


def test_pipelined_build_spans_land_on_worker_track(mesh, tmp_path):
    opts = _synthetic_opts(tmp_path, op="ring",
                           sweep="8,32,64,128", precompile=2)
    d = Driver(opts, mesh, err=io.StringIO())
    d.run()
    spans = d.tracer.records
    builds = [s for s in spans if s["kind"] == "build"]
    assert builds and all(s["thread"] == "worker" for s in builds)
    # the overlap the phase-sum gate proves numerically, as geometry:
    # at least one worker build overlaps a main-thread measure span
    assert len(build_measure_overlaps(spans)) >= 1
    # builds parent to the sweep anchor (when opened after the sweep
    # span) or to nothing (the pipeline's head start) — never to a
    # main-thread point
    by_id = {s["span_id"]: s for s in spans}
    for b in builds:
        parent = by_id.get(b["parent_id"])
        assert parent is None or parent["kind"] == "sweep"


def test_stop_vote_spans(mesh):
    from tpu_perf.adaptive import AdaptiveConfig, PointController

    tr = SpanTracer("job", retain=True, perf_ns=FakeNs())
    c = PointController(AdaptiveConfig(ci_rel=0.5, min_runs=2, max_runs=9),
                        vote=lambda local: local)
    for i in range(1, 4):
        c.observe(1.0)
        if c.should_stop(i, tracer=tr):
            break
    votes = [s for s in tr.records if s["kind"] == "stop_vote"]
    assert votes and votes[0]["attrs"]["run_id"] == 2


# -- chaos joins + anomaly context --------------------------------------


@pytest.fixture(scope="module")
def chaos_folder(mesh, tmp_path_factory):
    """A bounded chaos soak with spans on: spike + drop + hook_fail."""
    folder = tmp_path_factory.mktemp("chaos-spans")
    faults = [
        FaultSpec(kind="spike", op="ring", nbytes=32, start=8, end=12,
                  magnitude=30.0),
        FaultSpec(kind="drop_run", op="ring", nbytes=8, start=13, end=16),
        FaultSpec(kind="hook_fail", start=18, end=20),
    ]
    opts = Options(op="ring", sweep="8,32", iters=1, num_runs=-1,
                   fence="block", logfolder=str(folder), spans=True,
                   health=True, health_warmup=3, stats_every=5,
                   synthetic_s=1e-3, fault_seed=7, faults=faults)
    Driver(opts, mesh, err=io.StringIO(), max_runs=24).run()
    return folder


def test_chaos_join_completeness(chaos_folder):
    from tpu_perf.faults import read_ledger
    from tpu_perf.report import collect_paths, read_rows
    from tpu_perf.schema import CHAOS_PREFIX, HEALTH_PREFIX, SPANS_PREFIX

    spans = read_span_records(collect_paths(
        str(chaos_folder), prefix=SPANS_PREFIX, include_open=True))
    rows = read_rows(glob.glob(str(chaos_folder / "tpu-*.log")))
    events = read_events(collect_paths(
        str(chaos_folder), prefix=HEALTH_PREFIX, include_open=True))
    ledger = read_ledger(collect_paths(
        str(chaos_folder), prefix=CHAOS_PREFIX, include_open=True))
    assert rows and events
    assert any(r.get("record") == "fault" for r in ledger)
    assert join_completeness(spans, rows=rows, events=events,
                             ledger=ledger) == []
    # the daemon's global run ids make the ledger join exact without a
    # span column (its byte-identity contract keeps it span-free)
    fault = next(r for r in ledger
                 if r.get("record") == "fault" and r.get("run_id"))
    hits = resolve_run_span(spans, run_id=fault["run_id"],
                            op=fault.get("op") or None)
    assert len(hits) == 1
    # injections and the hook's forced rotation left activity spans
    kinds = {s["kind"] for s in spans}
    assert "inject" in kinds and "ingest_hook" in kinds


def test_anomaly_context_names_enclosing_and_concurrent(chaos_folder):
    from tpu_perf.report import collect_paths
    from tpu_perf.schema import HEALTH_PREFIX, SPANS_PREFIX

    spans = read_span_records(collect_paths(
        str(chaos_folder), prefix=SPANS_PREFIX, include_open=True))
    events = read_events(collect_paths(
        str(chaos_folder), prefix=HEALTH_PREFIX, include_open=True))
    ctx = anomaly_context(events, spans)
    assert ctx
    hook_rows = [c for c in ctx if c["event"].kind == "hook_fail"]
    assert hook_rows
    assert hook_rows[0]["span"] is not None
    assert any(s["kind"] == "ingest_hook"
               for s in hook_rows[0]["concurrent"])
    md = anomaly_to_markdown(ctx)
    assert "| hook_fail |" in md and "ingest_hook (" in md


def test_report_renders_anomaly_context(chaos_folder, capsys):
    from tpu_perf.cli import main

    assert main(["report", str(chaos_folder)]) == 0
    out = capsys.readouterr().out
    assert "### Anomaly context" in out
    assert "| hook_fail |" in out


def test_timeline_cli_export_and_check(chaos_folder, tmp_path, capsys):
    from tpu_perf.cli import main

    out_path = str(tmp_path / "timeline.json")
    assert main(["timeline", str(chaos_folder), "-o", out_path,
                 "--check"]) == 0
    err = capsys.readouterr().err
    assert "join complete" in err
    with open(out_path) as fh:
        data = json.load(fh)
    assert validate_chrome_trace(data) == []
    assert not os.path.exists(out_path + ".tmp")  # atomic write
    # no spans anywhere -> loud exit 1
    assert main(["timeline", str(tmp_path)]) == 1


def test_timeline_cli_requires_dir_for_check(chaos_folder, capsys):
    from tpu_perf.cli import main

    (slog,) = glob.glob(str(chaos_folder / "spans-*.log"))
    assert main(["timeline", slog, "--check"]) == 2


def test_write_timeline_atomic(tmp_path):
    path = str(tmp_path / "sub" / "t.json")
    write_timeline(path, "{}\n")
    with open(path) as fh:
        assert fh.read() == "{}\n"
    assert not os.path.exists(path + ".tmp")


# -- linkmap spans -------------------------------------------------------


def test_linkmap_spans_flag(tmp_path, capsys):
    from tpu_perf.cli import main

    rc = main(["linkmap", "--mesh", "2x2", "--synthetic", "0.001",
               "--seed", "7", "-b", "4K", "-l", str(tmp_path), "--spans"])
    assert rc == 0
    (slog,) = glob.glob(str(tmp_path / "spans-*.log"))
    spans = read_span_records([slog])
    scheds = [s for s in spans if s["kind"] == "probe_schedule"]
    assert scheds
    # probe records carry the enclosing schedule span id
    (llog,) = glob.glob(str(tmp_path / "linkmap-*.log"))
    with open(llog) as fh:
        recs = [json.loads(ln) for ln in fh.read().splitlines()]
    probes = [r for r in recs if r["record"] == "probe"]
    sched_ids = {s["span_id"] for s in scheds}
    assert probes and all(p["span_id"] in sched_ids for p in probes)


def test_linkmap_spans_needs_logfolder(capsys):
    from tpu_perf.cli import main

    assert main(["linkmap", "--mesh", "2x2", "--synthetic", "0.001",
                 "--spans"]) == 2


def test_linkmap_records_span_free_without_flag(tmp_path):
    from tpu_perf.cli import main

    assert main(["linkmap", "--mesh", "2x2", "--synthetic", "0.001",
                 "--seed", "7", "-b", "4K", "-l", str(tmp_path)]) == 0
    (llog,) = glob.glob(str(tmp_path / "linkmap-*.log"))
    with open(llog) as fh:
        recs = [json.loads(ln) for ln in fh.read().splitlines()]
    assert all("span_id" not in r for r in recs)  # pre-span bytes


def test_two_jobs_sharing_a_folder_join_per_job(mesh, tmp_path, capsys):
    # span IDs restart per job; the check must scope by job_id or every
    # record would match both jobs' same-ID spans
    from tpu_perf.cli import main

    for uuid in ("job-aaa", "job-bbb"):
        Driver(_synthetic_opts(tmp_path, uuid=uuid, op="ring"), mesh,
               err=io.StringIO()).run()
    out_path = str(tmp_path / "t.json")
    assert main(["timeline", str(tmp_path), "-o", out_path,
                 "--check"]) == 0
    assert "join complete: 16 row(s)" in capsys.readouterr().err


def test_untraced_job_sharing_folder_makes_no_join_claim(mesh, tmp_path,
                                                         capsys):
    # a spans-off run next to a traced one must not fail the audit: its
    # rows carry no span column and its job emitted no spans
    from tpu_perf.cli import main

    Driver(_synthetic_opts(tmp_path, uuid="job-off", op="ring",
                           spans=False), mesh, err=io.StringIO()).run()
    Driver(_synthetic_opts(tmp_path, uuid="job-on", op="ring"), mesh,
           err=io.StringIO()).run()
    assert main(["timeline", str(tmp_path), "-o",
                 str(tmp_path / "t.json"), "--check"]) == 0
    assert "join complete" in capsys.readouterr().err


def test_rank_filter_with_check_audits_that_rank_only(chaos_folder,
                                                      tmp_path, capsys):
    from tpu_perf.cli import main

    out_path = str(tmp_path / "t.json")
    assert main(["timeline", str(chaos_folder), "--rank", "0",
                 "-o", out_path, "--check"]) == 0
    assert "join complete" in capsys.readouterr().err


def test_finite_sweep_hook_fail_ledger_entry_still_resolves(mesh, tmp_path):
    # a hook_fail ledger entry carries op="" and a finite sweep's run_id
    # restarts per point: the op-less entry cannot name ONE point, so
    # matching any same-run_id run span counts as resolved
    from tpu_perf.faults import read_ledger
    from tpu_perf.report import collect_paths, read_rows
    from tpu_perf.schema import CHAOS_PREFIX, SPANS_PREFIX

    faults = [FaultSpec(kind="hook_fail", start=2, end=3)]
    opts = _synthetic_opts(tmp_path, faults=faults)
    Driver(opts, mesh, err=io.StringIO()).run()
    spans = read_span_records(collect_paths(
        str(tmp_path), prefix=SPANS_PREFIX, include_open=True))
    rows = read_rows(glob.glob(str(tmp_path / "tpu-*.log")))
    ledger = read_ledger(collect_paths(
        str(tmp_path), prefix=CHAOS_PREFIX, include_open=True))
    hook_entries = [r for r in ledger if r.get("kind") == "hook_fail"]
    assert hook_entries  # the fault fired
    assert join_completeness(spans, rows=rows, ledger=ledger) == []


def test_linkmap_sick_link_events_resolve_to_schedule_span(tmp_path,
                                                           capsys):
    # a traced linkmap sweep's link_degraded events are stamped with the
    # probe's enclosing probe_schedule span, so --check passes and the
    # anomaly context names the schedule
    import json as _json

    from tpu_perf.cli import main

    spec = tmp_path / "fault.json"
    spec.write_text(_json.dumps({"faults": [{
        "kind": "spike", "op": "link:(1,2)>(1,3)", "rank": 0,
        "magnitude": 30.0,
    }]}))
    logdir = tmp_path / "logs"
    rc = main(["linkmap", "--mesh", "2x4", "--synthetic", "0.001",
               "--seed", "7", "-b", "64K", "--faults", str(spec),
               "-l", str(logdir), "--spans"])
    assert rc == 6  # the sick link
    capsys.readouterr()
    out_path = str(tmp_path / "t.json")
    assert main(["timeline", str(logdir), "-o", out_path, "--check"]) == 0
    assert "join complete" in capsys.readouterr().err
    from tpu_perf.report import collect_paths
    from tpu_perf.schema import HEALTH_PREFIX, SPANS_PREFIX

    spans = read_span_records(collect_paths(
        str(logdir), prefix=SPANS_PREFIX, include_open=True))
    events = read_events(collect_paths(
        str(logdir), prefix=HEALTH_PREFIX, include_open=True))
    assert events and all(ev.span_id for ev in events)
    (ctx,) = anomaly_context(events, spans)
    assert ctx["span"] is not None
    assert ctx["span"]["kind"] == "probe_schedule"


# -- satellites ----------------------------------------------------------


def test_exporter_adaptive_gauges():
    from tpu_perf.health.exporter import render_textfile

    text = render_textfile([], {}, {}, adaptive={
        "runs_saved": 42, "last_ci_rel": 0.031,
    })
    assert "tpu_perf_adaptive_runs_saved_total 42" in text
    assert "tpu_perf_adaptive_last_ci_rel 0.031" in text
    assert "tpu_perf_adaptive" not in render_textfile([], {}, {})


def test_driver_exporter_carries_adaptive_gauges(mesh, tmp_path):
    import random

    class SeededDriver(Driver):
        def _measure(self, built, built_hi):
            counts = self.__dict__.setdefault("_seed_counts", {})
            key = (built.name, built.nbytes)
            n = counts[key] = counts.get(key, 0) + 1
            rnd = random.Random(f"{built.name}:{built.nbytes}:{n}")
            return 1e-3 * (1.0 + 0.01 * (rnd.random() - 0.5))

    textfile = str(tmp_path / "tpu-perf.prom")
    opts = Options(op="ring", sweep="8,64", iters=1, num_runs=30,
                   fence="block", health=True, health_textfile=textfile,
                   ci_rel=0.05, min_runs=5)
    SeededDriver(opts, mesh, err=io.StringIO()).run()
    with open(textfile) as fh:
        text = fh.read()
    assert "tpu_perf_adaptive_runs_saved_total 50" in text
    assert "tpu_perf_adaptive_last_ci_rel" in text


def test_phase_sidecar_written_atomically(mesh, tmp_path):
    d = Driver(_synthetic_opts(tmp_path, spans=False), mesh,
               err=io.StringIO())
    d.run()
    (sidecar,) = glob.glob(str(tmp_path / "phase-*.json"))
    with open(sidecar) as fh:
        data = json.load(fh)
    assert "phase" in data
    assert not glob.glob(str(tmp_path / "phase-*.json.tmp"))


def test_read_phases_resolves_sidecars_next_to_a_file_target(mesh, tmp_path):
    from tpu_perf.report import read_phases

    Driver(_synthetic_opts(tmp_path, spans=False), mesh,
           err=io.StringIO()).run()
    (log,) = glob.glob(str(tmp_path / "tpu-*.log"))
    entries = read_phases(log)  # a single rotating-log FILE target
    assert entries and entries[0]["job_id"] == "job-fixed"
    assert read_phases(str(tmp_path)) == entries
    assert read_phases(str(tmp_path / "nope-*.log")) == []


def test_report_phase_table_for_file_target(mesh, tmp_path, capsys):
    from tpu_perf.cli import main

    Driver(_synthetic_opts(tmp_path, spans=False), mesh,
           err=io.StringIO()).run()
    (log,) = glob.glob(str(tmp_path / "tpu-*.log"))
    assert main(["report", log]) == 0
    assert "### Harness phases" in capsys.readouterr().out


def test_spans_family_rides_the_ingest_pass(chaos_folder, tmp_path):
    from tpu_perf.ingest.pipeline import LocalDirBackend, run_all_ingest_passes

    sink = str(tmp_path / "sink")
    n = run_all_ingest_passes(str(chaos_folder), skip_newest=0,
                              backend=LocalDirBackend(sink))
    assert n >= 1
    assert glob.glob(os.path.join(sink, "spans-*.log"))
