import pytest

from tpu_perf.config import DEF_ITERS, LOG_REFRESH_TIME_SEC, Options


def test_defaults_match_reference():
    # mpi_perf.c:388-392: iters=10, buff=456131, runs=1, bidir, blocking
    opts = Options()
    assert opts.iters == DEF_ITERS == 10
    assert opts.buff_sz == 456131
    assert opts.num_runs == 1
    assert not opts.uni_dir
    assert not opts.nonblocking
    assert opts.ppn == 1
    assert LOG_REFRESH_TIME_SEC == 900  # mpi_perf.c:16


def test_uuid_minted_per_instance():
    a, b = Options(), Options()
    assert a.uuid != b.uuid
    assert len(a.uuid) == 36


def test_infinite_mode():
    assert Options(num_runs=-1).infinite
    assert not Options(num_runs=5).infinite
    with pytest.raises(ValueError):
        Options(num_runs=0)
    with pytest.raises(ValueError):
        Options(num_runs=-2)


def test_validation():
    with pytest.raises(ValueError):
        Options(iters=0)
    with pytest.raises(ValueError):
        Options(buff_sz=-1)
    with pytest.raises(ValueError):
        Options(ppn=0)
    with pytest.raises(ValueError):
        Options(uni_dir=True, nonblocking=True)
    with pytest.raises(ValueError):
        Options(mesh_shape=(2, 4), mesh_axes=("x",))


def test_mesh_config():
    opts = Options(mesh_shape=(2, 4), mesh_axes=("dcn", "ici"))
    assert opts.mesh_shape == (2, 4)
