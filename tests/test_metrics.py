import pytest

from tpu_perf.metrics import (
    KNOWN_OPS,
    alg_bandwidth_gbps,
    bus_bandwidth_gbps,
    latency_us,
    legacy_gbps,
    percentile,
    summarize,
)


def test_alg_bandwidth():
    # 1 GB in 1 s = 1 GB/s
    assert alg_bandwidth_gbps(10**9, 1.0) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        alg_bandwidth_gbps(8, 0.0)


def test_bus_factors():
    n = 8
    t = 1.0
    nbytes = 10**9
    assert bus_bandwidth_gbps("allreduce", nbytes, t, n) == pytest.approx(2 * 7 / 8)
    assert bus_bandwidth_gbps("all_gather", nbytes, t, n) == pytest.approx(7 / 8)
    assert bus_bandwidth_gbps("reduce_scatter", nbytes, t, n) == pytest.approx(7 / 8)
    assert bus_bandwidth_gbps("all_to_all", nbytes, t, n) == pytest.approx(7 / 8)
    assert bus_bandwidth_gbps("broadcast", nbytes, t, n) == pytest.approx(1.0)
    assert bus_bandwidth_gbps("pingpong", nbytes, t, n) == pytest.approx(1.0)
    # degenerate single device: factor 1, no division by zero
    assert bus_bandwidth_gbps("allreduce", nbytes, t, 1) == pytest.approx(1.0)
    # local HBM family: stream reads+writes (2); the single-sided
    # instruments move nbytes exactly once per iteration (1)
    assert bus_bandwidth_gbps("hbm_stream", nbytes, t, 1) == pytest.approx(2.0)
    assert bus_bandwidth_gbps("hbm_read", nbytes, t, 1) == pytest.approx(1.0)
    assert bus_bandwidth_gbps("hbm_write", nbytes, t, 1) == pytest.approx(1.0)
    with pytest.raises(ValueError):
        bus_bandwidth_gbps("nope", nbytes, t, n)


def test_known_ops_cover_baseline_configs():
    # every op named by BASELINE.json's five configs must be known
    for op in ("pingpong", "allreduce", "broadcast", "all_gather",
               "reduce_scatter", "all_to_all", "ppermute", "ring", "halo"):
        assert op in KNOWN_OPS


def test_legacy_gbps_matches_reference_formula():
    # mpi_perf.c:538-539: 8*buff*iters*dirs*1e-9/t
    buff, iters, t = 456131, 10, 0.5
    assert legacy_gbps(buff, iters, True, t) == pytest.approx(8 * buff * iters * 2 * 1e-9 / t)
    assert legacy_gbps(buff, iters, False, t) == pytest.approx(8 * buff * iters * 1e-9 / t)


def test_latency_us():
    assert latency_us(1.0, 1000) == pytest.approx(1000.0)
    assert latency_us(1.0, 1000, round_trip=True) == pytest.approx(500.0)
    with pytest.raises(ValueError):
        latency_us(1.0, 0)


def test_percentile():
    xs = [1.0, 2.0, 3.0, 4.0, 5.0]
    assert percentile(xs, 0) == 1.0
    assert percentile(xs, 50) == 3.0
    assert percentile(xs, 100) == 5.0
    assert percentile(xs, 25) == 2.0
    assert percentile([7.0], 99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


def test_summarize():
    s = summarize([2.0, 1.0, 3.0])
    assert s["min"] == 1.0
    assert s["max"] == 3.0
    assert s["avg"] == pytest.approx(2.0)
    assert s["p50"] == 2.0
