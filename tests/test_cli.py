import time

from tpu_perf.cli import build_parser, main
from tpu_perf.schema import RESULT_HEADER, LegacyRow, ResultRow


def test_parser_reference_flags():
    args = build_parser().parse_args(
        ["run", "-l", "/tmp/x", "-i", "50", "-b", "4M", "-u", "-r", "-1", "-p", "10", "-f", "hosts"]
    )
    assert args.logfolder == "/tmp/x"
    assert args.iters == 50
    assert args.size == "4M"
    assert args.unidir
    assert args.runs == -1
    assert args.ppn == 10
    assert args.group1_file == "hosts"


def test_parser_reference_spelling_verbatim():
    # the reference's run scripts spell booleans with values ("-u 1",
    # run-hbv3.sh:28) and use -f for the group file, -n for its host count,
    # -i for iters, -l for the logfolder (mpi_perf.c:273-339)
    args = build_parser().parse_args(
        ["run", "-f", "group1", "-n", "1", "-p", "10", "-u", "1",
         "-r", "-1", "-i", "10", "-b", "456131", "-l", "/mnt/tcp-logs"]
    )
    assert args.group1_file == "group1"
    assert args.group1_hosts == 1
    assert args.unidir is True
    assert args.iters == 10
    assert args.logfolder == "/mnt/tcp-logs"
    off = build_parser().parse_args(["run", "-u", "0", "-x", "1"])
    assert off.unidir is False and off.nonblocking is True


def test_stale_pre_rename_n_flag_fails_loudly(capsys):
    # "-n 100" used to mean iters; silently ignoring it would benchmark
    # 10x fewer messages — it must error and point at -i
    rc = main(["run", "--op", "allreduce", "-n", "100", "-r", "1"])
    assert rc == 2
    assert "-i" in capsys.readouterr().err


def test_cli_run_end_to_end_csv(eight_devices, capsys):
    """The minimum end-to-end slice (SURVEY.md §7 step 2): a sweep on CPU
    devices producing valid extended-schema CSV on stdout."""
    rc = main(["run", "--op", "allreduce", "--sweep", "8,64", "-i", "1", "-r", "2"])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    assert out[0] == RESULT_HEADER
    rows = [ResultRow.from_csv(line) for line in out[1:]]
    assert len(rows) == 4  # 2 sizes x 2 runs
    assert {r.nbytes for r in rows} == {8, 64}
    assert all(r.backend == "jax" for r in rows)
    assert all(r.busbw_gbps > 0 for r in rows)


def test_cli_run_writes_rotating_log(eight_devices, tmp_path, capsys):
    rc = main([
        "run", "--op", "ring", "-i", "1", "-r", "2", "-b", "64",
        "-l", str(tmp_path), "--csv",
    ])
    assert rc == 0
    logs = list(tmp_path.glob("tcp-*.log"))
    assert len(logs) == 1
    lines = logs[0].read_text().splitlines()
    assert len(lines) == 2
    LegacyRow.from_csv(lines[0])  # parses in the reference schema
    out = capsys.readouterr().out.splitlines()
    assert out[0] == RESULT_HEADER


def test_cli_mesh_flag(eight_devices, capsys):
    rc = main([
        "run", "--op", "hier_allreduce", "--mesh", "2x4", "--axes", "dcn,ici",
        "-i", "1", "-r", "1", "-b", "256",
    ])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    row = ResultRow.from_csv(out[1])
    assert row.n_devices == 8


def test_cli_ingest_subcommand(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("TPU_PERF_INGEST", f"local:{tmp_path / 'sink'}")
    src = tmp_path / "logs"
    src.mkdir()
    for i, age in enumerate((300, 200, 100)):
        p = src / f"tcp-{i}.log"
        p.write_text("x\n")
        t = time.time() - age
        import os

        os.utime(p, (t, t))
    rc = main(["ingest", "-d", str(src), "-f", "1"])
    assert rc == 0
    assert len(list((tmp_path / "sink").iterdir())) == 2


def test_cli_windowed_exchange(eight_devices, capsys):
    rc = main([
        "run", "--op", "exchange", "--window", "4", "-b", "64", "-i", "1", "-r", "1",
    ])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    row = ResultRow.from_csv(out[1])
    assert row.nbytes == 64  # per-message size (mpi_perf.c BufferSize)
    assert row.iters == 4 * 1  # window multiplies the message count


def test_cli_window_requires_windowed_kernel(capsys):
    rc = main(["run", "--op", "allreduce", "--window", "4", "-r", "1"])
    assert rc == 2


def test_pingpong_row_internally_consistent(eight_devices, capsys):
    rc = main(["run", "--op", "pingpong", "-b", "1024", "-i", "2", "-r", "1"])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    row = ResultRow.from_csv(out[1])
    # nbytes / lat_us must equal algbw (both use one-way time)
    import pytest as _pytest

    assert row.nbytes / row.lat_us * 1e-3 == _pytest.approx(row.algbw_gbps, rel=0.01)


def test_cli_ops_list(capsys):
    rc = main(["ops"])
    assert rc == 0
    out = capsys.readouterr().out.split()
    assert "allreduce" in out and "pingpong" in out and "hier_allreduce" in out


def test_cli_mpi_backend_dry_run(capsys):
    # VERDICT r2 #1: --backend mpi is a real backend now; --dry-run prints
    # the exact launch line (full coverage in test_mpi_launch.py)
    rc = main(["run", "--backend", "mpi", "--op", "exchange", "-b", "64K",
               "--dry-run"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mpi_perf_shim -np 2 --" in out and "-x 1" in out


def test_cli_jax_backend_rejects_dry_run(capsys):
    rc = main(["run", "--backend", "jax", "--dry-run"])
    assert rc == 2
    assert "--dry-run" in capsys.readouterr().err


def test_cli_broken_pipe_exits_141(monkeypatch):
    # ADVICE r3: a reader hanging up must NOT read as success — the gate
    # subcommands (report --diff -> 3, grid -> 4) compute their verdict
    # after rendering, so `| grep -q` truncating the pipe means the gate
    # never ran.  141 = 128+SIGPIPE, what `set -o pipefail` expects.
    import os
    import sys

    import tpu_perf.cli as cli_mod

    def _raiser(_args):
        raise BrokenPipeError

    monkeypatch.setattr(cli_mod, "_cmd_ops", _raiser)
    # the handler points the real stdout fd at devnull (fine in the CLI
    # process, which exits right after); restore it so the rest of the
    # pytest session keeps its output
    saved = os.dup(sys.stdout.fileno())
    try:
        assert main(["ops"]) == 141
    finally:
        os.dup2(saved, sys.stdout.fileno())
        os.close(saved)
