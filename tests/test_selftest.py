"""The selftest subsystem: payload numerics validation of the kernels
(the rx-buffer check the reference never performs, mpi_perf.c:75-80)."""

import jax
import pytest

from tpu_perf.parallel import make_mesh
from tpu_perf.selftest import EXPECTATIONS, SelftestResult, format_results, run_selftest


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh()


def test_sample_ops_pass(mesh):
    # one op per kernel family (the full set runs in `tpu-perf selftest`)
    ops = ["allreduce", "barrier", "exchange", "halo", "pl_allreduce"]
    results = run_selftest(mesh, ops=ops, nbytes=256)
    assert [r.op for r in results] == ops
    assert all(r.status == "ok" for r in results), results


def test_chained_iters_compose_the_model(mesh):
    # iters > 1 runs the fori_loop carry and composes the numpy model the
    # same number of times — a carry-convention bug passes at iters=1 but
    # not here (e.g. ring: 3 chained shifts == roll by 3)
    ops = ["ring", "allreduce", "exchange", "pl_ring", "pl_reduce_scatter"]
    results = run_selftest(mesh, ops=ops, nbytes=256, iters=3)
    assert all(r.status == "ok" for r in results), results


def test_chained_iters_catch_carry_bugs(mesh, monkeypatch):
    # a model wrong only under composition: correct once, broken at 2+
    import tpu_perf.selftest as st

    calls = {"n": 0}
    real = st.EXPECTATIONS["ring"]

    def once_right(x):
        calls["n"] += 1
        return real(x) if calls["n"] == 1 else x

    monkeypatch.setitem(st.EXPECTATIONS, "ring", once_right)
    (res,) = run_selftest(mesh, ops=["ring"], nbytes=256, iters=2)
    assert res.status == "fail"


def test_bfloat16_models_within_tolerance(mesh):
    # the dtype ladder must hold for reduced precision too (incl. the
    # matmul ops, whose per-op floor composes with the dtype rtol)
    ops = ["allreduce", "ring", "mxu_gemm", "overlap_ring", "hbm_stream"]
    results = run_selftest(mesh, ops=ops, nbytes=4096, dtype="bfloat16")
    assert all(r.status == "ok" for r in results), results


def test_every_op_has_a_model_or_skip(mesh):
    from tpu_perf.ops import OP_BUILDERS
    from tpu_perf.ops.pallas_ring import PALLAS_OPS

    for op in list(OP_BUILDERS) + list(PALLAS_OPS):
        assert op in EXPECTATIONS, f"no numeric model for {op}"


def test_detects_wrong_numerics(mesh, monkeypatch):
    # sabotage the model: a real corruption must be reported, not hidden
    import tpu_perf.selftest as st

    monkeypatch.setitem(st.EXPECTATIONS, "ring", lambda x: x)  # wrong: no shift
    (res,) = run_selftest(mesh, ops=["ring"], nbytes=256)
    assert res.status == "fail" and "elements off" in res.detail


def test_topology_skips(eight_devices):
    mesh5 = make_mesh(devices=jax.devices()[:5])
    results = {r.op: r for r in run_selftest(
        mesh5, ops=["exchange", "ring", "hier_allreduce"], nbytes=64
    )}
    assert results["exchange"].status == "skip"  # odd device count
    assert results["ring"].status == "ok"
    assert results["hier_allreduce"].status == "skip"  # flat mesh

    mesh2d = make_mesh((2, 4), ("dcn", "ici"))
    results = {r.op: r for r in run_selftest(
        mesh2d, ops=["hier_allreduce", "pingpong"], nbytes=64
    )}
    assert results["hier_allreduce"].status == "ok"
    assert results["pingpong"].status == "skip"


def test_unknown_op_raises_not_skips(mesh):
    # a typo in --ops must fail loudly, not pass the health check as SKIP
    with pytest.raises(ValueError, match="unknown op"):
        run_selftest(mesh, ops=["alreduce"])


def test_cli_unknown_op_exits_2(mesh):
    from tpu_perf.cli import main

    assert main(["selftest", "--ops", "alreduce"]) == 2


def test_format_results_summary():
    out = format_results([
        SelftestResult("a", "ok", ""),
        SelftestResult("b", "skip", "why"),
        SelftestResult("c", "fail", "bad"),
    ])
    assert "1 ok, 1 skipped, 1 failed" in out


def test_cli_selftest_exit_codes(mesh, capsys, monkeypatch):
    from tpu_perf.cli import main

    assert main(["selftest", "--ops", "allreduce,ring", "-b", "256"]) == 0
    out = capsys.readouterr().out
    assert "allreduce" in out and "2 ok" in out

    import tpu_perf.selftest as st

    monkeypatch.setitem(st.EXPECTATIONS, "ring", lambda x: x)
    assert main(["selftest", "--ops", "ring", "-b", "256"]) == 1


def test_barrier_rows_latency_only(mesh):
    from tpu_perf.config import Options
    from tpu_perf.runner import run_point

    opts = Options(op="barrier", iters=4, num_runs=2)
    point = run_point(opts, mesh, 456131)
    assert point.nbytes == 4  # fixed 1-element payload regardless of -b
    rows = point.rows(opts.uuid)
    assert all(r.busbw_gbps == 0.0 and r.algbw_gbps == 0.0 for r in rows)
    assert all(r.lat_us > 0 for r in rows)


def test_barrier_sweep_collapses_to_one_point(mesh):
    # sweeping a fixed-payload op would time the identical kernel per size
    from tpu_perf.config import Options
    from tpu_perf.runner import run_sweep

    opts = Options(op="barrier", iters=2, num_runs=1, sweep="8,64,1K")
    points = list(run_sweep(opts, mesh))
    assert len(points) == 1
