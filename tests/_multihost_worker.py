"""Worker process for the 2-process multi-host integration test.

Run as: python _multihost_worker.py <process_id> <coordinator_port>
Prints one JSON line with the observations the parent test asserts on.
Not a pytest module (leading underscore keeps it out of collection).
"""

import io
import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from tpu_perf.parallel import (
        allreduce_times,
        claim_cpu_devices,
        initialize_distributed,
        make_hybrid_mesh,
    )

    assert claim_cpu_devices(2)

    import jax

    initialize_distributed(
        f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.local_devices()) == 2
    assert len(jax.devices()) == 4

    mesh = make_hybrid_mesh()
    assert dict(mesh.shape) == {"dcn": 2, "ici": 2}, dict(mesh.shape)

    # NaN contribution is excluded from the cross-process triple
    triple = allreduce_times(float("nan") if pid == 1 else 2.5)
    assert triple == {"min": 2.5, "max": 2.5, "avg": 2.5}, triple

    # all-NaN yields NaNs, never a crash or a phantom zero
    import math

    triple = allreduce_times(float("nan"))
    assert all(math.isnan(v) for v in triple.values()), triple

    # full driver run over the hybrid mesh, slope-fenced, with a
    # cross-host heartbeat every 2 runs — the lockstep-critical path
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver

    opts = Options(
        op="hier_allreduce",
        iters=2,
        num_runs=4,
        buff_sz=256,
        stats_every=2,
        fence="slope",
    )
    err = io.StringIO()
    rows = Driver(opts, mesh, err=err).run()

    # extern mode across 2 processes: rank 0 = client, rank 1 = server,
    # with peer IPs exchanged via the cross-process allgather
    ext_opts = Options(
        extern_cmd="bench {role} {ip} {port}", num_runs=1, buff_sz=64
    )
    ext_err = io.StringIO()
    ext_rows = Driver(ext_opts, mesh, err=ext_err).run()
    assert len(ext_rows) == 1 and ext_rows[0].op == "extern"
    extern_line = [
        ln for ln in ext_err.getvalue().splitlines() if ln.startswith("bench ")
    ][0]

    print(
        json.dumps(
            {
                "pid": pid,
                "rows": len(rows),
                "heartbeats": err.getvalue().count("hosts min"),
                "n_devices": rows[0].n_devices if rows else 0,
                "extern": extern_line,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
