"""Worker process for the multi-process integration tests.

Run as: python _multihost_worker.py <process_id> <coordinator_port> [n_procs]
Prints one JSON line with the observations the parent test asserts on.
Not a pytest module (leading underscore keeps it out of collection).
"""

import io
import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    n_procs = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from tpu_perf.parallel import (
        allreduce_times,
        claim_cpu_devices,
        initialize_distributed,
        make_hybrid_mesh,
    )

    assert claim_cpu_devices(2)

    import jax

    initialize_distributed(
        f"127.0.0.1:{port}", num_processes=n_procs, process_id=pid
    )
    assert jax.process_count() == n_procs, jax.process_count()
    assert len(jax.local_devices()) == 2
    assert len(jax.devices()) == 2 * n_procs

    mesh = make_hybrid_mesh()
    assert dict(mesh.shape) == {"dcn": n_procs, "ici": 2}, dict(mesh.shape)

    # NaN contribution is excluded from the cross-process triple (every
    # non-1 process contributes 2.5, process 1 contributes nothing)
    triple = allreduce_times(float("nan") if pid == 1 else 2.5)
    assert triple == {"min": 2.5, "max": 2.5, "avg": 2.5}, triple

    # all-NaN yields NaNs, never a crash or a phantom zero
    import math

    triple = allreduce_times(float("nan"))
    assert all(math.isnan(v) for v in triple.values()), triple

    # FULL-WINDOW triple (VERDICT r4 #8): every sample of every host's
    # window is covered, not just the last — process 0 contributes the
    # window [2.0] (avg 2.0), everyone else [4.0, 8.0] (avg 6.0); min and
    # max span ALL samples, avg is the mean of per-host averages
    win = [2.0] if pid == 0 else [4.0, 8.0]
    triple = allreduce_times(win)
    want_avg = (2.0 + 6.0 * (n_procs - 1)) / n_procs
    assert triple["min"] == 2.0 and triple["max"] == 8.0, triple
    assert abs(triple["avg"] - want_avg) < 1e-9, (triple, want_avg)
    # an empty window enters the collective as NaN and is excluded
    triple = allreduce_times([] if pid == 0 else [3.0])
    assert triple == {"min": 3.0, "max": 3.0, "avg": 3.0}, triple

    # numpy scalars are accepted (ISSUE 5 satellite): the adaptive
    # controller's lockstep stop-vote allreduces such values
    import numpy as np

    triple = allreduce_times(np.float64(2.0))
    assert triple == {"min": 2.0, "max": 2.0, "avg": 2.0}, triple

    # full driver run over the hybrid mesh, slope-fenced, with a
    # cross-host heartbeat every 2 runs — the lockstep-critical path.
    # Processes 1 and 2 DROP their first two samples (the value is
    # discarded AFTER the collectives executed, exactly the noise-drop
    # path): their first heartbeat window is empty, so they must enter
    # the boundary collective with NaN while the others carry data — the
    # discipline that keeps a lossy process from deadlocking the fleet.
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    import tpu_perf.driver as driver_mod

    drop_first_two = n_procs >= 4 and pid in (1, 2)
    real_slope_sample = driver_mod.slope_sample
    seen = {"n": 0}

    def dropping_slope_sample(*args, **kwargs):
        seen["n"] += 1
        s = real_slope_sample(*args, **kwargs)
        return None if (drop_first_two and seen["n"] <= 2) else s

    driver_mod.slope_sample = dropping_slope_sample

    opts = Options(
        op="hier_allreduce",
        iters=2,
        num_runs=4,
        buff_sz=256,
        stats_every=2,
        fence="slope",
    )
    err = io.StringIO()
    rows = Driver(opts, mesh, err=err).run()
    driver_mod.slope_sample = real_slope_sample

    # --- trace fence, multi-host (VERDICT r4 #2) ---
    # (a) the CPU runtime records no device lanes: the fail-fast
    # TraceUnavailableError must surface cleanly on EVERY process (each
    # raises after the same number of collective executions, so no
    # process is left blocked in a collective)
    import tpu_perf.timing as timing_mod
    from tpu_perf.timing import RunTimes
    from tpu_perf.traceparse import TraceParseError, TraceUnavailableError

    trace_opts = Options(
        op="hier_allreduce", iters=2, num_runs=4, buff_sz=256,
        stats_every=2, fence="trace",
    )
    trace_failfast = False
    try:
        Driver(trace_opts, mesh, err=io.StringIO()).run()
    except TraceUnavailableError:
        trace_failfast = True

    # (b) inject a fake device-lane capture to exercise per-process
    # parse + lockstep drop + heartbeat: processes 1 (and 2 when 4-wide)
    # glitch EVERY capture (TraceParseError), so their points skip with
    # num_runs None records while the others carry real samples — the
    # boundary collectives must stay in lockstep (completion is the
    # deadlock assertion)
    glitching = pid in (1, 2) if n_procs >= 4 else pid == 1
    real_time_trace = timing_mod.time_trace

    def fake_time_trace(step_lo, step_hi, x, iters_lo, iters_hi, num_runs,
                        *, warmup_runs=0, name_hint=None, trace_dir=None):
        if glitching:
            raise TraceParseError("injected: device lane dropped a launch")
        return RunTimes(samples=[1e-6] * num_runs, warmup_s=0.0,
                        overhead_s=0.0)

    timing_mod.time_trace = fake_time_trace
    trace_err = io.StringIO()
    trace_drv = Driver(
        Options(op="hier_allreduce", iters=2, num_runs=4, buff_sz=256,
                stats_every=2, fence="trace"),
        mesh, err=trace_err,
    )
    trace_rows = trace_drv.run()
    timing_mod.time_trace = real_time_trace
    trace_dropped = sum(trace_drv.dropped_runs.values())

    # (c) --fence auto resolves identically on every process (the probe
    # is deterministic per runtime kind): slope here, with real rows
    auto_drv = Driver(
        Options(op="hier_allreduce", iters=2, num_runs=2, buff_sz=256,
                fence="auto"),
        mesh, err=io.StringIO(),
    )
    auto_rows = auto_drv.run()
    auto_fence = auto_drv.opts.fence

    # multi-op family over the hybrid mesh: every process builds the same
    # (op, size) list in the same order, so the cross-process collectives
    # stay in lockstep across the family boundary (the op SWITCH is the
    # new lockstep-critical edge a single-op run never crosses)
    fam_opts = Options(
        op="allreduce,hbm_stream", iters=2, num_runs=2, buff_sz=256,
        fence="slope",
    )
    fam_rows = Driver(fam_opts, mesh, err=io.StringIO()).run()

    # extern mode across the processes: first half clients, second half
    # servers, peer IPs exchanged via the cross-process allgather
    ext_opts = Options(
        extern_cmd="bench {role} {ip} {port}", num_runs=1, buff_sz=64
    )
    ext_err = io.StringIO()
    ext_rows = Driver(ext_opts, mesh, err=ext_err).run()
    assert len(ext_rows) == 1 and ext_rows[0].op == "extern"
    extern_line = [
        ln for ln in ext_err.getvalue().splitlines() if ln.startswith("bench ")
    ][0]

    print(
        json.dumps(
            {
                "pid": pid,
                "rows": len(rows),
                "heartbeats": err.getvalue().count("hosts min"),
                "n_devices": rows[0].n_devices if rows else 0,
                "extern": extern_line,
                "family_ops": sorted({r.op for r in fam_rows}),
                "family_rows": len(fam_rows),
                "trace_failfast": trace_failfast,
                "trace_rows": len(trace_rows),
                "trace_dropped": trace_dropped,
                "trace_heartbeats": trace_err.getvalue().count("hosts min"),
                "auto_fence": auto_fence,
                "auto_rows": len(auto_rows),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
