"""Worker process for the multi-process integration tests.

Run as: python _multihost_worker.py <process_id> <coordinator_port> [n_procs]
Prints one JSON line with the observations the parent test asserts on.
Not a pytest module (leading underscore keeps it out of collection).
"""

import io
import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    n_procs = int(sys.argv[3]) if len(sys.argv) > 3 else 2

    from tpu_perf.parallel import (
        allreduce_times,
        claim_cpu_devices,
        initialize_distributed,
        make_hybrid_mesh,
    )

    assert claim_cpu_devices(2)

    import jax

    initialize_distributed(
        f"127.0.0.1:{port}", num_processes=n_procs, process_id=pid
    )
    assert jax.process_count() == n_procs, jax.process_count()
    assert len(jax.local_devices()) == 2
    assert len(jax.devices()) == 2 * n_procs

    mesh = make_hybrid_mesh()
    assert dict(mesh.shape) == {"dcn": n_procs, "ici": 2}, dict(mesh.shape)

    # NaN contribution is excluded from the cross-process triple (every
    # non-1 process contributes 2.5, process 1 contributes nothing)
    triple = allreduce_times(float("nan") if pid == 1 else 2.5)
    assert triple == {"min": 2.5, "max": 2.5, "avg": 2.5}, triple

    # all-NaN yields NaNs, never a crash or a phantom zero
    import math

    triple = allreduce_times(float("nan"))
    assert all(math.isnan(v) for v in triple.values()), triple

    # full driver run over the hybrid mesh, slope-fenced, with a
    # cross-host heartbeat every 2 runs — the lockstep-critical path.
    # Processes 1 and 2 DROP their first two samples (the value is
    # discarded AFTER the collectives executed, exactly the noise-drop
    # path): their first heartbeat window is empty, so they must enter
    # the boundary collective with NaN while the others carry data — the
    # discipline that keeps a lossy process from deadlocking the fleet.
    from tpu_perf.config import Options
    from tpu_perf.driver import Driver
    import tpu_perf.driver as driver_mod

    drop_first_two = n_procs >= 4 and pid in (1, 2)
    real_slope_sample = driver_mod.slope_sample
    seen = {"n": 0}

    def dropping_slope_sample(*args, **kwargs):
        seen["n"] += 1
        s = real_slope_sample(*args, **kwargs)
        return None if (drop_first_two and seen["n"] <= 2) else s

    driver_mod.slope_sample = dropping_slope_sample

    opts = Options(
        op="hier_allreduce",
        iters=2,
        num_runs=4,
        buff_sz=256,
        stats_every=2,
        fence="slope",
    )
    err = io.StringIO()
    rows = Driver(opts, mesh, err=err).run()
    driver_mod.slope_sample = real_slope_sample

    # multi-op family over the hybrid mesh: every process builds the same
    # (op, size) list in the same order, so the cross-process collectives
    # stay in lockstep across the family boundary (the op SWITCH is the
    # new lockstep-critical edge a single-op run never crosses)
    fam_opts = Options(
        op="allreduce,hbm_stream", iters=2, num_runs=2, buff_sz=256,
        fence="slope",
    )
    fam_rows = Driver(fam_opts, mesh, err=io.StringIO()).run()

    # extern mode across the processes: first half clients, second half
    # servers, peer IPs exchanged via the cross-process allgather
    ext_opts = Options(
        extern_cmd="bench {role} {ip} {port}", num_runs=1, buff_sz=64
    )
    ext_err = io.StringIO()
    ext_rows = Driver(ext_opts, mesh, err=ext_err).run()
    assert len(ext_rows) == 1 and ext_rows[0].op == "extern"
    extern_line = [
        ln for ln in ext_err.getvalue().splitlines() if ln.startswith("bench ")
    ][0]

    print(
        json.dumps(
            {
                "pid": pid,
                "rows": len(rows),
                "heartbeats": err.getvalue().count("hosts min"),
                "n_devices": rows[0].n_devices if rows else 0,
                "extern": extern_line,
                "family_ops": sorted({r.op for r in fam_rows}),
                "family_rows": len(fam_rows),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
