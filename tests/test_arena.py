"""Collective-algorithm arena (ISSUE 10, tpu_perf.arena).

Coverage contract:

* every registered (collective, algorithm) pair's step output equals the
  native lowering's on the seeded example inputs — bit-identical for the
  movement algorithms, within the dtype's reduction-order tolerance for
  the reducing ones — across dtypes and 1D/2D mesh shapes;
* the registry satisfies the arena's shape (>= 4 algorithms, each
  covering >= 2 of {allreduce, all_gather, reduce_scatter});
* the algo column round-trips through the 20-field row schema and every
  older width still parses;
* the driver sweeps algorithms head-to-head (block AND fused fences),
  the report splits curves per algorithm, excludes arena rows from the
  clean compare pivots, and renders the crossover table with a winner
  at every size;
* invalid combinations (unknown algo, pow2 mismatch, pallas/extern/mpi
  targets) fail loudly before anything compiles.
"""

import dataclasses
import io

import numpy as np
import pytest

from tpu_perf.arena import (
    ALGORITHM_NAMES,
    ARENA_ALGORITHMS,
    ARENA_COLLECTIVES,
    algorithms_for,
    algos_for_op,
    arena_body_builder,
    is_compatible,
)
from tpu_perf.compilepipe import CompileSpec
from tpu_perf.config import Options
from tpu_perf.runner import algos_for_options, run_point
from tpu_perf.schema import RESULT_HEADER, ResultRow, timestamp_now


# ------------------------------------------------------------ registry


def test_registry_shape():
    # the arena's advertised matrix: >= 4 algorithms, each implementing
    # >= 2 collectives, every original collective covered by >= 2 (the
    # all_to_all family is newer — one shifted-exchange ring so far)
    assert len(ALGORITHM_NAMES) >= 4
    for algo in ALGORITHM_NAMES:
        colls = [c for c, a in ARENA_ALGORITHMS if a == algo]
        assert len(colls) >= 2, (algo, colls)
    for coll in ("allreduce", "all_gather", "reduce_scatter"):
        assert len(algorithms_for(coll)) >= 2, coll
    assert "all_to_all" in ARENA_COLLECTIVES
    assert "ring" in algorithms_for("all_to_all")


def test_pow2_only_validation():
    # rhd pairs ranks by XOR: a 6-device axis must fail loudly on an
    # explicit request and be skipped (with a note) by the expansion
    with pytest.raises(ValueError, match="power-of-two"):
        arena_body_builder("allreduce", "rhd", 6)
    assert not is_compatible("allreduce", "rhd", 6)
    assert is_compatible("allreduce", "rhd", 8)
    err = io.StringIO()
    algos = algos_for_op("allreduce", 6, err=err)
    assert "rhd" not in algos and "ring" in algos
    assert "skipping allreduce@rhd" in err.getvalue()


def test_unknown_pairs_fail_loudly():
    with pytest.raises(ValueError, match="no arena decompositions"):
        arena_body_builder("hbm_stream", "ring", 8)
    with pytest.raises(ValueError, match="registered"):
        arena_body_builder("reduce_scatter", "bruck", 8)
    with pytest.raises(ValueError, match="registered"):
        arena_body_builder("allreduce", "warp", 8)


def test_algos_for_options_expansion_and_strictness():
    opts = Options(op="allreduce", algo="all")
    assert algos_for_options(opts, "allreduce", 8) == \
        ["native"] + list(algorithms_for("allreduce"))
    # non-arena ops ride an "all" sweep natively
    assert algos_for_options(opts, "hbm_stream", 8) == ["native"]
    # explicit families validate strictly, including per-op coverage
    opts = dataclasses.replace(opts, algo="ring,native")
    assert algos_for_options(opts, "allreduce", 8) == ["ring", "native"]
    opts = dataclasses.replace(opts, algo="bruck")
    with pytest.raises(ValueError, match="registered"):
        algos_for_options(opts, "reduce_scatter", 8)
    opts = dataclasses.replace(opts, algo="ring")
    with pytest.raises(ValueError, match="no arena decompositions"):
        algos_for_options(opts, "hbm_stream", 8)


def test_options_validation():
    with pytest.raises(ValueError, match="jax backend"):
        Options(op="allreduce", algo="ring", backend="mpi")
    with pytest.raises(ValueError, match="must not be empty"):
        Options(op="allreduce", algo="")
    with pytest.raises(ValueError, match="window"):
        Options(op="exchange", algo="ring", nonblocking=True, window=4)


def test_compile_spec_keys_on_algo():
    a = CompileSpec.make("allreduce", 1024, 10, algo="ring")
    b = CompileSpec.make("allreduce", 1024, 10, algo="rhd")
    c = CompileSpec.make("allreduce", 1024, 10)
    assert len({a, b, c}) == 3
    assert c.algo == "native"


# ------------------------------------------------------- schema widths


def _row(**kw):
    base = dict(
        timestamp=timestamp_now(), job_id="j", backend="jax",
        op="allreduce", nbytes=1024, iters=4, run_id=1, n_devices=8,
        lat_us=10.0, algbw_gbps=1.0, busbw_gbps=1.75, time_ms=0.04,
    )
    base.update(kw)
    return ResultRow(**base)


def test_arena_row_roundtrips_at_20_fields():
    row = _row(algo="ring")
    line = row.to_csv()
    # the algo column always rides with the (possibly empty) span
    # column, so 19 fields stays unambiguously a traced native row
    assert len(line.split(",")) == 20
    back = ResultRow.from_csv(line)
    assert back.algo == "ring" and back.span_id == ""
    traced = _row(algo="bruck", span_id="r7")
    back = ResultRow.from_csv(traced.to_csv())
    assert (back.algo, back.span_id) == ("bruck", "r7")


def test_native_rows_keep_pre_arena_widths():
    assert len(_row().to_csv().split(",")) == 18
    assert len(_row(span_id="r1").to_csv().split(",")) == 19


def test_old_width_rows_still_parse():
    full = _row(algo="ring", span_id="r1").to_csv().split(",")
    for width, algo, span in ((12, "", ""), (13, "", ""), (15, "", ""),
                              (18, "", ""), (19, "", "r1"),
                              (20, "ring", "r1")):
        back = ResultRow.from_csv(",".join(full[:width]))
        assert (back.algo, back.span_id) == (algo, span), width
    with pytest.raises(ValueError, match="fields"):
        # one column past the widest accepted width (24, load)
        ResultRow.from_csv(",".join(
            (full + [""] * 24)[:24] + ["surplus"]))
    # the emitted header stays an accepted parser width (the R4 gate)
    assert len(RESULT_HEADER.split(",")) in (12, 13, 15, 18, 19, 20, 21,
                                             22)


# ------------------------------------------------- numerics (device)


def _mesh(shape=(), axes=()):
    from tpu_perf.parallel import make_mesh

    return make_mesh(shape, axes)


def _run_pair(op, algo, *, mesh=None, axis=None, nbytes=256,
              dtype="float32", iters=2):
    import jax

    from tpu_perf.ops import build_op

    mesh = mesh if mesh is not None else _mesh()
    native = build_op(op, mesh, nbytes, iters, dtype=dtype, axis=axis)
    arena = build_op(op, mesh, nbytes, iters, dtype=dtype, axis=axis,
                     algo=algo)
    assert arena.algo == algo and native.algo == "native"
    assert arena.nbytes == native.nbytes  # head-to-head on one curve key
    want = np.asarray(jax.block_until_ready(
        native.step(native.example_input)), dtype=np.float64)
    got = np.asarray(jax.block_until_ready(
        arena.step(arena.example_input)), dtype=np.float64)
    return want, got


#: reduction-order tolerance per dtype (movement ops are exact)
_RTOL = {"float32": 5e-6, "bfloat16": 5e-2, "float16": 5e-3}


@pytest.mark.parametrize("coll,algo", sorted(ARENA_ALGORITHMS))
def test_numerics_parity_float32(coll, algo, eight_devices):
    want, got = _run_pair(coll, algo)
    if coll == "all_gather":
        np.testing.assert_array_equal(got, want)
    else:
        np.testing.assert_allclose(got, want, rtol=_RTOL["float32"])


@pytest.mark.parametrize("algo", sorted(algorithms_for("allreduce")))
def test_allreduce_parity_odd_payload(algo, eight_devices):
    # 8 bytes of f32 on 8 devices: 2 elements per device, NOT divisible
    # by n — the block algorithms' virtual-padding path
    want, got = _run_pair("allreduce", algo, nbytes=8)
    np.testing.assert_allclose(got, want, rtol=_RTOL["float32"])


def test_allreduce_parity_bfloat16(eight_devices):
    for algo in algorithms_for("allreduce"):
        want, got = _run_pair("allreduce", algo, dtype="bfloat16")
        np.testing.assert_allclose(got, want, rtol=_RTOL["bfloat16"])


def test_allgather_parity_int32(eight_devices):
    # movement algorithms are dtype-agnostic and bit-exact
    for algo in algorithms_for("all_gather"):
        want, got = _run_pair("all_gather", algo, dtype="int32")
        np.testing.assert_array_equal(got, want)


def test_parity_on_2d_mesh_axis(eight_devices):
    # a (2, 4) mesh, collective on the 4-wide axis: arena schedules run
    # per-row in lockstep exactly like the pairwise ops
    mesh = _mesh((2, 4), ("a", "b"))
    for coll in ("allreduce", "reduce_scatter"):
        for algo in algorithms_for(coll):
            want, got = _run_pair(coll, algo, mesh=mesh, axis="b")
            np.testing.assert_allclose(got, want, rtol=_RTOL["float32"])


def test_arena_needs_single_axis(eight_devices):
    from tpu_perf.ops import build_op

    mesh = _mesh((2, 4), ("a", "b"))
    with pytest.raises(ValueError, match="single mesh axis"):
        build_op("allreduce", mesh, 256, 2, algo="ring")


def test_pallas_and_window_rejected(eight_devices):
    from tpu_perf.ops import build_op

    mesh = _mesh()
    with pytest.raises(ValueError, match="pallas"):
        build_op("pl_ring", mesh, 256, 2, algo="ring")
    with pytest.raises(ValueError, match="window"):
        build_op("all_gather", mesh, 256, 2, algo="ring", window=4)


# ------------------------------------------------------ harness e2e


def test_run_point_with_algo(eight_devices):
    opts = Options(op="allreduce", buff_sz=512, iters=2, num_runs=2)
    res = run_point(opts, _mesh(), 512, algo="ring")
    assert res.algo == "ring"
    rows = res.rows("job")
    assert all(r.algo == "ring" for r in rows)
    assert all(r.op == "allreduce" for r in rows)


def test_driver_sweeps_algorithms_head_to_head(eight_devices, tmp_path):
    from tpu_perf.driver import Driver

    err = io.StringIO()
    opts = Options(op="allreduce,all_gather", algo="all", sweep="8,2048",
                   iters=1, num_runs=2, logfolder=str(tmp_path))
    drv = Driver(opts, _mesh(), err=err)
    rows = drv.run()
    seen = {(r.op, r.algo or "native") for r in rows}
    want = {("allreduce", a) for a in
            ["native"] + list(algorithms_for("allreduce"))}
    want |= {("all_gather", a) for a in
             ["native"] + list(algorithms_for("all_gather"))}
    assert seen == want
    # every (op, algo) pair measured every size with the full budget
    assert len(rows) == len(want) * 2 * 2
    # the rotating log round-trips the algo column
    import glob

    from tpu_perf.report import read_rows

    logged = read_rows(sorted(glob.glob(str(tmp_path / "tpu-*.log"))))
    assert {(r.op, r.algo or "native") for r in logged} == want


def test_driver_fused_fence_arena(eight_devices):
    # acceptance: arena algorithms under --fence fused — one dispatch
    # per point, rows carrying the algorithm
    from tpu_perf.driver import Driver

    err = io.StringIO()
    opts = Options(op="allreduce", algo="native,ring,binomial",
                   sweep="8,2048", iters=1, num_runs=3, fence="fused")
    drv = Driver(opts, _mesh(), err=err)
    rows = drv.run()
    assert {(r.algo or "native") for r in rows} == \
        {"native", "ring", "binomial"}
    assert drv.fused_totals["points"] == 6
    assert drv.fused_totals["measure_dispatches"] == 6
    assert len(rows) == 18


def test_chaos_ledger_identical_with_native_algo(eight_devices, tmp_path):
    # the algo plumbing is provably inert for native soaks: the same
    # seeded synthetic chaos soak, with and without the flag spelled
    # out, writes byte-identical ledgers (the 0b/0g precedent)
    import glob

    from tpu_perf.driver import Driver
    from tpu_perf.faults import FaultSpec

    ledgers = []
    for sub, algo in (("a", "native"), ("b", "native")):
        folder = tmp_path / sub
        opts = Options(op="ring", sweep="8,32", iters=1, num_runs=-1,
                       algo=algo, synthetic_s=0.001, fault_seed=7,
                       faults=[FaultSpec(kind="spike", op="ring",
                                         nbytes=32, start=3, end=5,
                                         magnitude=10.0)],
                       logfolder=str(folder), stats_every=5)
        Driver(opts, _mesh(), err=io.StringIO(), max_runs=20).run()
        text = b"".join(
            open(p, "rb").read() for p in
            sorted(glob.glob(str(folder / "chaos-*.log"))))
        ledgers.append(text)
    assert ledgers[0] == ledgers[1] and ledgers[0]


# ------------------------------------------------------------- report


def _mk_rows(op, algo, lat_us, nbytes=1024, mode="oneshot", n=3):
    # busbw tracks the latency (both derive from the same per-op time)
    # so latency- and bandwidth-judged views rank identically
    return [
        _row(op=op, algo="" if algo == "native" else algo,
             nbytes=nbytes, lat_us=lat_us, busbw_gbps=1000.0 / lat_us,
             mode=mode, run_id=i + 1)
        for i in range(n)
    ]


def test_aggregate_splits_curves_per_algorithm():
    from tpu_perf.report import aggregate

    rows = _mk_rows("allreduce", "native", 10.0) + \
        _mk_rows("allreduce", "ring", 5.0)
    points = aggregate(rows)
    assert {(p.algo, p.lat_us["p50"]) for p in points} == \
        {("native", 10.0), ("ring", 5.0)}


def test_compare_pivots_exclude_arena_rows():
    from tpu_perf.report import (
        aggregate, compare, compare_chaos, compare_pallas,
    )

    rows = (_mk_rows("allreduce", "native", 10.0)
            + _mk_rows("allreduce", "ring", 5.0)
            + [dataclasses.replace(r, backend="mpi")
               for r in _mk_rows("allreduce", "native", 12.0)])
    points = aggregate(rows)
    (cmp,) = compare(points)
    # the faster arena curve must NOT have stolen the jax slot
    assert cmp.jax.lat_us["p50"] == 10.0 and cmp.jax.algo == "native"
    assert all(c.pallas is None or c.pallas.algo == "native"
               for c in compare_pallas(points))
    assert compare_chaos(points) == []


def test_compare_arena_crossover_and_markdown():
    from tpu_perf.report import (
        aggregate, arena_to_markdown, compare_arena,
    )

    rows = []
    # small size: native wins; large size: ring wins 2x
    for nbytes, native_lat, ring_lat in ((64, 5.0, 9.0),
                                         (1 << 20, 100.0, 50.0)):
        rows += _mk_rows("allreduce", "native", native_lat, nbytes=nbytes)
        rows += _mk_rows("allreduce", "ring", ring_lat, nbytes=nbytes)
        rows += _mk_rows("allreduce", "bruck", ring_lat * 2, nbytes=nbytes)
    cross = compare_arena(aggregate(rows))
    assert [(c.nbytes, c.best[0]) for c in cross] == \
        [(64, "native"), (1 << 20, "ring")]
    small, large = cross
    assert small.native_vs_best == pytest.approx(1.0)
    assert large.native_vs_best == pytest.approx(2.0)
    md = arena_to_markdown(cross)
    assert "ring wins" in md and "native holds" in md
    # a winner is named at every size
    for line in md.splitlines()[2:]:
        assert line.split("|")[5].strip()


def test_compare_arena_excludes_chaos_and_requires_arena_rows():
    from tpu_perf.report import aggregate, compare_arena

    # chaos-perturbed arena rows must not crown a winner
    rows = _mk_rows("allreduce", "native", 10.0) + \
        _mk_rows("allreduce", "ring", 1.0, mode="chaos")
    assert compare_arena(aggregate(rows)) == []
    # native-only folders render no crossover section at all
    assert compare_arena(aggregate(_mk_rows("allreduce", "native",
                                            10.0))) == []


def test_to_markdown_renders_op_algo_cell():
    from tpu_perf.report import aggregate, to_markdown

    md = to_markdown(aggregate(_mk_rows("allreduce", "ring", 5.0)))
    assert "| allreduce[ring] |" in md


def test_to_json_roundtrips_algo():
    from tpu_perf.report import aggregate, points_from_artifact, to_json

    rows = _mk_rows("allreduce", "ring", 5.0) + \
        _mk_rows("allreduce", "native", 7.0)
    blob = to_json(aggregate(rows))
    assert '"algo": "ring"' in blob and '"algo": "native"' not in blob


def test_diff_pairs_per_algorithm(tmp_path):
    from tpu_perf.report import aggregate, diff_points

    base = aggregate(_mk_rows("allreduce", "native", 10.0)
                     + _mk_rows("allreduce", "ring", 10.0))
    new = aggregate(_mk_rows("allreduce", "native", 10.0)
                    + _mk_rows("allreduce", "ring", 30.0))
    diffs = diff_points(base, new)
    verdicts = {(d.algo, d.verdict) for d in diffs}
    assert ("ring", "regressed") in verdicts
    assert ("native", "ok") in verdicts


def test_fleet_rollup_folds_arena_under_decorated_op():
    from tpu_perf.fleet.rollup import HostRollup

    roll = HostRollup("host-a", "/tmp/x")
    for r in (_mk_rows("allreduce", "native", 10.0)
              + _mk_rows("allreduce", "ring", 5.0)):
        roll.fold_row(r)
    ops = {k[0] for k in roll.points}
    assert ops == {"allreduce", "allreduce[ring]"}


def test_health_baselines_key_per_algorithm(eight_devices):
    # an arena monitor soak must NOT pool the algorithms' (systematically
    # different) latency streams into one (op, nbytes) health baseline —
    # the decorated op[algo] label keys each algorithm's own point state
    from tpu_perf.driver import Driver

    opts = Options(op="allreduce", algo="native,binomial", buff_sz=64,
                   iters=1, num_runs=-1, health=True, health_warmup=2,
                   stats_every=4)
    drv = Driver(opts, _mesh(), err=io.StringIO(), max_runs=8)
    drv.run()
    keys = set(drv.health._points)
    assert ("allreduce", 64) in keys
    assert ("allreduce[binomial]", 64) in keys


def test_conformance_matches_decorated_health_ops():
    # a fault spec targets the RAW op the injector filters on; a health
    # event raised under an algorithm's decorated baseline still counts
    # as the fault being caught
    from tpu_perf.faults.conformance import _event_matches
    from tpu_perf.faults.spec import FaultSpec
    from tpu_perf.health.events import HealthEvent

    f = FaultSpec(kind="spike", op="allreduce", start=1, end=10)

    def ev(op):
        return HealthEvent(
            timestamp="t", job_id="j", kind="spike", severity="warning",
            op=op, nbytes=0, dtype="float32", run_id=5, window=0,
            observed=1.0, baseline=0.1,
        )

    assert _event_matches(f, "spike", ev("allreduce[ring]"), 1, 10, 0)
    assert _event_matches(f, "spike", ev("allreduce"), 1, 10, 0)
    assert not _event_matches(f, "spike", ev("reduce_scatter[ring]"),
                              1, 10, 0)


def test_run_sweep_rejects_algo_family(eight_devices):
    from tpu_perf.runner import run_sweep

    opts = Options(op="allreduce", algo="all", buff_sz=512, iters=1)
    with pytest.raises(ValueError, match="families"):
        next(run_sweep(opts, _mesh()))


# ------------------------------------------------------- lint contract


def test_arena_is_linted_and_clean():
    # satellite: the arena is in the manifest's linted zones (R1
    # deterministic + R2 lockstep over its ppermute schedules) and the
    # shipped tree has zero findings there
    from tpu_perf.analysis import (
        default_manifest_path, default_root, lint_tree, load_manifest,
    )

    root = default_root()
    manifest = load_manifest(default_manifest_path(), root)
    assert "tpu_perf/arena/" in manifest.deterministic_zones
    res = lint_tree(root, manifest)
    assert [f for f in res.findings if "arena" in f.path] == []


# ---------------------------------------------------------------- CLI


def test_cli_run_algo_flag(eight_devices, capsys):
    from tpu_perf.cli import main

    # a mixed native+arena stream: the CSV table must stay RECTANGULAR
    # (native rows padded to the advertised header width)
    rc = main(["run", "--op", "allreduce", "--algo", "native,ring",
               "-b", "512", "-i", "1", "-r", "1", "--csv"])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    assert out[0] == RESULT_HEADER + ",span_id,algo"
    width = out[0].count(",")
    assert all(ln.count(",") == width for ln in out[1:])
    assert {ResultRow.from_csv(ln).algo for ln in out[1:]} == {"", "ring"}


def test_cli_arena_defaults(eight_devices, capsys):
    # the arena subcommand defaults to every decomposition of every
    # arena collective; explicit flags narrow it
    from tpu_perf.cli import main

    rc = main(["arena", "--op", "reduce_scatter", "--algo",
               "native,binomial", "-b", "512", "-i", "1", "-r", "1",
               "--csv"])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    algos = {ResultRow.from_csv(ln).algo for ln in out[1:]}
    assert algos == {"", "binomial"}


def test_cli_rejects_algo_on_mpi_backend(capsys):
    from tpu_perf.cli import main

    assert main(["run", "--backend", "mpi", "--algo", "ring",
                 "-r", "1"]) == 2
    assert "jax backend" in capsys.readouterr().err
