"""True multi-process integration: 2 controller processes, 2 CPU devices
each, joined via jax.distributed with a local coordinator (cross-process
collectives ride Gloo on CPU).  Exercises what the single-process tests
cannot: process_count()==2 hybrid meshes, the cross-host heartbeat
collective in lockstep, and NaN exclusion in allreduce_times.
"""

import json
import os
import socket
import subprocess
import sys

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_driver_run():
    port = _free_port()
    env = dict(os.environ)
    # repo root only: drop any sitecustomize dir that force-registers a
    # TPU plugin in the children
    env["PYTHONPATH"] = _REPO_ROOT
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            text=True,
            cwd=_REPO_ROOT,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, errtxt = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{out}\n{errtxt}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # one worker failing leaves its sibling blocked in a collective;
        # never leak it past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    for o in outs:
        # slope fencing may drop noise-degenerate samples, but the
        # 4-run loop with 2 warm-ups should land most of them
        assert o["rows"] >= 2
        assert o["n_devices"] == 4
    # the heartbeat triple is printed by rank 0 only, at the run-2 and
    # run-4 boundaries — a boundary whose window lost every sample to
    # noise prints nothing, so tolerate 1
    assert 1 <= by_pid[0]["heartbeats"] <= 2
    assert by_pid[1]["heartbeats"] == 0
    # extern pairing across processes: rank 0 dials rank 1's server port
    assert by_pid[0]["extern"].startswith("bench client ")
    assert by_pid[1]["extern"].startswith("bench server ")
    assert by_pid[0]["extern"].split()[-1] == by_pid[1]["extern"].split()[-1]
