"""True multi-process integration: N controller processes, 2 CPU devices
each, joined via jax.distributed with a local coordinator (cross-process
collectives ride Gloo on CPU).  Exercises what the single-process tests
cannot: process_count()==N hybrid meshes, hier_allreduce over a DCN axis
wider than 2, the cross-host heartbeat collective in lockstep — including
processes whose samples all dropped entering the boundary with NaN — and
extern pairing across 2 and 4 processes.
"""

import json
import os
import socket
import subprocess
import sys

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(n_procs: int) -> dict[int, dict]:
    port = _free_port()
    env = dict(os.environ)
    # repo root only: drop any sitecustomize dir that force-registers a
    # TPU plugin in the children
    env["PYTHONPATH"] = _REPO_ROOT
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port), str(n_procs)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            text=True,
            cwd=_REPO_ROOT,
        )
        for pid in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, errtxt = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{out}\n{errtxt}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # one worker failing leaves its siblings blocked in a collective;
        # never leak them past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    by_pid = {o["pid"]: o for o in outs}
    assert set(by_pid) == set(range(n_procs))
    return by_pid


def _assert_trace_fence(by_pid: dict[int, dict], glitching: set[int]) -> None:
    """VERDICT r4 #2: the trace fence's multi-host story, asserted from
    the worker observations (the workers COMPLETING is itself the
    no-deadlock assertion)."""
    for pid, o in by_pid.items():
        # (a) CPU runtime: TraceUnavailableError fail-fast on EVERY process
        assert o["trace_failfast"], (pid, o)
        # (b) injected captures: glitching processes skip all 4 runs (no
        # retry multi-host) yet complete; the others carry 4 real rows
        if pid in glitching:
            assert o["trace_rows"] == 0 and o["trace_dropped"] == 4, (pid, o)
        else:
            assert o["trace_rows"] == 4 and o["trace_dropped"] == 0, (pid, o)
        # (c) --fence auto resolved identically everywhere (slope on CPU;
        # row count is noise-dependent under retries=0, completion isn't)
        assert o["auto_fence"] == "slope" and o["auto_rows"] <= 2, (pid, o)
    # rank 0 is non-glitching: its two boundary heartbeats carry the
    # cross-host triple even though glitching peers contributed NaN
    assert by_pid[0]["trace_heartbeats"] == 2, by_pid[0]


def test_two_process_driver_run():
    by_pid = _run_workers(2)
    for o in by_pid.values():
        # slope fencing may drop noise-degenerate samples, but the
        # 4-run loop with 2 warm-ups should land most of them
        assert o["rows"] >= 2
        assert o["n_devices"] == 4
    # the heartbeat triple is printed by rank 0 only, at the run-2 and
    # run-4 boundaries — a boundary whose window lost every sample to
    # noise prints nothing, so tolerate 1
    assert 1 <= by_pid[0]["heartbeats"] <= 2
    assert by_pid[1]["heartbeats"] == 0
    # extern pairing across processes: rank 0 dials rank 1's server port
    assert by_pid[0]["extern"].startswith("bench client ")
    assert by_pid[1]["extern"].startswith("bench server ")
    assert by_pid[0]["extern"].split()[-1] == by_pid[1]["extern"].split()[-1]
    # instrument family across processes: the op switch (the new
    # lockstep-critical edge) did not deadlock, and surviving rows carry
    # only family ops (slope noise may drop an op's whole 2-run window,
    # so the exact set is not deterministic — completion is)
    for o in by_pid.values():
        assert set(o["family_ops"]) <= {"allreduce", "hbm_stream"}, o
        assert o["family_ops"] and o["family_rows"] >= 2, o
    _assert_trace_fence(by_pid, glitching={1})


def test_four_process_driver_run():
    # VERDICT r2 #6: dcn=4 — hier_allreduce over a >2 DCN axis, heartbeat
    # lockstep with processes 1 and 2 dropping their first two samples
    # (empty first window -> NaN entry into the boundary collective), and
    # extern pairing across 4
    by_pid = _run_workers(4)
    for pid, o in by_pid.items():
        if o["rows"]:
            assert o["n_devices"] == 8
        if pid in (1, 2):
            # first two samples force-dropped; real timing noise may take
            # the remaining two as well (retries=0 in multi-host slope
            # mode), so only the ceiling is deterministic
            assert o["rows"] <= 2, o
        else:
            # same noise tolerance as the 2-process test: >= 2 of 4
            assert o["rows"] >= 2, o
    # the heartbeat triple prints only on a boundary where rank 0's own
    # window has data — noise can silence either boundary, so only the
    # ceiling is pinned; the load-bearing lockstep assertion is that all
    # four workers COMPLETED (no deadlock) despite 2 lossy processes
    # entering both boundary collectives with NaN
    assert by_pid[0]["heartbeats"] <= 2
    assert all(by_pid[p]["heartbeats"] == 0 for p in (1, 2, 3))
    # the op family's build/measure sequence stayed in lockstep across
    # all four processes (completion IS the assertion; per-run counts are
    # noise-dependent)
    for o in by_pid.values():
        assert set(o["family_ops"]) <= {"allreduce", "hbm_stream"}, o
    _assert_trace_fence(by_pid, glitching={1, 2})
    # pairing: 0<->2 and 1<->3 (first half clients, second half servers)
    for client, server in ((0, 2), (1, 3)):
        assert by_pid[client]["extern"].startswith("bench client ")
        assert by_pid[server]["extern"].startswith("bench server ")
        assert (by_pid[client]["extern"].split()[-1]
                == by_pid[server]["extern"].split()[-1])
