"""Headline benchmark hook (the driver runs this file).  The logic lives in
tpu_perf.bench so `tpu-perf bench` works from an installed package too."""

from tpu_perf.bench import main

if __name__ == "__main__":
    main()
