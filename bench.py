"""Headline benchmark: all-reduce bus bandwidth at the 4 MiB legacy point.

Runs on whatever devices are available (the driver runs this on one real TPU
chip; multi-chip ICI when present).  Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md "Published numbers": none),
so ``vs_baseline`` is reported against this framework's own documented
nominal target rather than a reference measurement: 10 GB/s bus bandwidth at
4 MiB — a deliberately conservative single-chip floor (one v5e chip's local
all-reduce is HBM-bound; multi-chip ICI runs will recalibrate it).
"""

from __future__ import annotations

import json

NOMINAL_BUSBW_GBPS = 10.0


def main() -> None:
    import jax

    from tpu_perf.config import Options
    from tpu_perf.metrics import percentile
    from tpu_perf.parallel import make_mesh
    from tpu_perf.runner import run_point
    from tpu_perf.sweep import LEGACY_BW_BUF_SZ

    mesh = make_mesh()
    n = len(jax.devices())
    opts = Options(op="allreduce", iters=20, num_runs=10, warmup_runs=2)
    point = run_point(opts, mesh, LEGACY_BW_BUF_SZ)
    rows = point.rows(opts.uuid)
    busbw = percentile([r.busbw_gbps for r in rows], 50)
    print(
        json.dumps(
            {
                "metric": f"allreduce_busbw_p50@4MiB[{n}dev]",
                "value": round(busbw, 3),
                "unit": "GB/s",
                "vs_baseline": round(busbw / NOMINAL_BUSBW_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
